"""Multi-model LoRA serving: adapter multiplexing at fleet scale.

The claims: N LoRA adapters decode through ONE fixed-shape compiled
batch (per-row bank slots are data — the ``decode_n`` program cache
stays at one entry across adapter churn), every multiplexed stream is
bit-equal to a dedicated single-adapter engine's (real tiny-llama
factory AND the sim arm), ``adapter=None`` everywhere is
byte-identical to the pre-adapter engine (outputs, slot logs,
decisions, metrics records, report keys, registry contents), the
budgeted ``AdapterCache`` honors LRU retention / pin-while-in-flight /
refusal-requeues with its resident+evictable+free census conserved,
``prefix_aware`` placement routes to adapter residency and replicates
hot adapters under load, ``Request.adapter`` round-trips JSONL with
legacy traces untouched, the metrics/trace adapter blocks appear ONLY
for multi-model traffic, and the ``serving_lora`` bench-gate family
passes its pass rows and fails its FAIL rows.
"""
import dataclasses
import json
import os
import sys

import pytest

import paddle_tpu as paddle
from paddle_tpu.models.nlp.llama_decode import (
    LoRAConfig, as_lora_config, lora_bank_hooks, synthesize_lora_deltas)
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.serving import (AdapterCache, AdapterStore,
                                ClusterRouter, QoSScheduler, Request,
                                ServingEngine, load_trace,
                                make_sim_serving, save_trace,
                                synthesize_trace,
                                synthesize_zipf_adapter_trace,
                                trace_stats)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 97
COSTS = {"prefill_unit": 1.0, "decode": 1.0, "adapter_upload": 1.0}


def _sim_store(n=4, prime=7919):
    return AdapterStore({f"a{k}": {"salt": prime * (k + 1)}
                         for k in range(n)})


def _sim_engine(lora_slots=None, adapters=None, slots=8, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", dict(COSTS))
    kw.setdefault("decode_chunk", 4)
    return ServingEngine(
        serving=make_sim_serving(max_len=64, page_size=8, slots=slots,
                                 vocab=509, lora_slots=lora_slots),
        slots=slots, policy="paged", adapters=adapters, **kw)


def _zipf(seed=0, n=40, n_adapters=4, **kw):
    kw.setdefault("base_frac", 0.2)
    kw.setdefault("churn_frac", 0.1)
    return synthesize_zipf_adapter_trace(seed=seed, n_requests=n,
                                        n_adapters=n_adapters, **kw)


# --- Request.adapter + trace round-trip -------------------------------------

def test_request_adapter_roundtrip(tmp_path):
    """The adapter field survives JSONL; the key is written only when
    set, so adapter-less records are byte-identical to PR 11's."""
    r = Request(rid="x", arrival=1.0, prompt=(1, 2), max_new_tokens=3,
                adapter="support-bot")
    assert Request.from_json(r.to_json()) == r
    plain = Request(rid="y", arrival=2.0, prompt=(3,), max_new_tokens=1)
    assert "adapter" not in plain.to_json()
    assert Request.from_json(plain.to_json()).adapter is None
    p = tmp_path / "t.jsonl"
    save_trace(str(p), [r, plain])
    back = load_trace(str(p))
    assert back == [r, plain]


def test_legacy_trace_jsonl_byte_identical(tmp_path):
    """An adapter-less trace's JSONL is byte-for-byte what the
    pre-adapter serializer wrote (no new key, no ordering drift)."""
    trace = synthesize_trace(seed=3, n_requests=6, vocab_size=VOCAB)
    p = tmp_path / "t.jsonl"
    save_trace(str(p), trace)
    for line, r in zip(open(p), trace):
        d = json.loads(line)
        assert set(d) <= {"rid", "arrival", "prompt", "max_new_tokens",
                          "prefix_group", "cancel_after", "tenant",
                          "priority", "deadline_ms"}
        assert d["rid"] == r.rid


def test_zipf_adapter_trace_shape():
    """Seeded determinism, rid-baked adapter ids, Zipf head heavier
    than tail, mixed-churn fields, JSONL round-trip."""
    a = _zipf(seed=7, n=400)
    b = _zipf(seed=7, n=400)
    assert a == b
    assert any(r.adapter is None and r.rid.endswith(".base")
               for r in a)
    counts = {}
    for r in a:
        if r.adapter is not None:
            assert r.rid.endswith("." + r.adapter)
            counts[r.adapter] = counts.get(r.adapter, 0) + 1
    assert counts["a0"] > counts["a3"]  # the Zipf skew
    assert any(r.cancel_after is not None for r in a)
    assert all(r.deadline_ms is not None for r in a)
    st = trace_stats(a)
    assert st["adapters"] == sorted(counts)
    assert st["adapter_requests"] == sum(counts.values())
    # adapter-less stats carry no adapter keys
    st0 = trace_stats(synthesize_trace(seed=0, n_requests=4))
    assert "adapters" not in st0 and "adapter_requests" not in st0
    with pytest.raises(ValueError, match="adapter"):
        synthesize_zipf_adapter_trace(n_adapters=0)


# --- AdapterCache units ------------------------------------------------------

def _cache(n_slots=4, n_adapters=6):
    store = _sim_store(n_adapters)
    sim = make_sim_serving(lora_slots=n_slots)
    return store, AdapterCache(store, n_slots, sim.init_adapter_bank,
                               sim.upload_adapter)


def test_cache_hit_miss_upload_and_bank_content():
    store, c = _cache(n_slots=3)
    s1, up1 = c.acquire("a0", "r1")
    assert up1 and s1 == 1 and int(c.bank[s1]) == 7919
    s2, up2 = c.acquire("a0", "r2")      # second pin: hit, same slot
    assert (s2, up2) == (s1, False)
    s3, up3 = c.acquire("a1", "r3")
    assert up3 and s3 == 2 and int(c.bank[s3]) == 7919 * 2
    assert c.cache_stats()["uploads"] == 2
    assert c.cache_stats()["hits"] == 1
    assert c.census_ok()


def test_cache_lru_eviction_order():
    """Released adapters park evictable in release order; a miss
    reclaims the LEAST recently parked first."""
    _, c = _cache(n_slots=3)
    c.acquire("a0", "r0")
    c.acquire("a1", "r1")
    c.release("a0", "r0")
    c.release("a1", "r1")        # LRU order now: a0, a1
    slot_a0 = c.slot_of("a0")
    c.acquire("a2", "r2")        # evicts a0 (oldest parked)
    assert not c.resident("a0") and c.resident("a1")
    assert c.slot_of("a2") == slot_a0
    assert c.cache_stats()["evictions"] == 1
    # revival: re-acquiring the survivor is a hit, not an upload
    _, up = c.acquire("a1", "r3")
    assert not up
    assert c.census_ok()


def test_cache_pin_survives_eviction_pressure():
    """A pinned adapter is never evicted: misses churn through the
    other slot while the pin holds, and its bank content is intact."""
    _, c = _cache(n_slots=3)
    c.acquire("a0", "live")          # pinned throughout
    for i, name in enumerate(("a1", "a2", "a3", "a4")):
        c.acquire(name, f"r{i}")
        c.release(name, f"r{i}")
    assert c.resident("a0")
    assert int(c.bank[c.slot_of("a0")]) == 7919
    assert c.cache_stats()["evictions"] == 3
    assert c.census_ok()


def test_cache_budget_refusal_mutates_nothing():
    """Every usable slot pinned -> MemoryError; the census and the
    pin table are untouched, and a later release unblocks."""
    _, c = _cache(n_slots=3)
    c.acquire("a0", "r0")
    c.acquire("a1", "r1")
    before = c.cache_stats()
    with pytest.raises(MemoryError, match="pinned"):
        c.acquire("a2", "r2")
    after = c.cache_stats()
    assert after["refusals"] == before["refusals"] + 1
    for k in ("resident_slots", "evictable_slots", "free_slots",
              "uploads"):
        assert after[k] == before[k]
    assert c.census_ok()
    c.release("a0", "r0")
    s, up = c.acquire("a2", "r2")    # now evicts a0
    assert up and c.census_ok()


def test_cache_acquire_exception_safe():
    """A raising upload hook (e.g. a rank-mismatched delta set caught
    by the real hook's shape check) must not leak the slot out of the
    census: free list / evictable LRU / stats restore exactly, the
    error stays loud, and the cache keeps serving."""
    store = AdapterStore({"good": {"salt": 1}, "bad": "boom",
                          "good2": {"salt": 2}})
    sim = make_sim_serving(lora_slots=3)

    def upload(bank, slot, deltas):
        if deltas == "boom":
            raise ValueError("delta shape mismatch")
        return sim.upload_adapter(bank, slot, deltas)
    c = AdapterCache(store, 3, sim.init_adapter_bank, upload)
    # free-list path
    before = c.cache_stats()
    with pytest.raises(ValueError, match="mismatch"):
        c.acquire("bad", "r0")
    assert c.cache_stats() == before and c.census_ok()
    # eviction path: fill both slots, park them, then fail an acquire
    c.acquire("good", "r1")
    c.acquire("good2", "r2")
    c.release("good", "r1")
    c.release("good2", "r2")
    before = c.cache_stats()
    with pytest.raises(ValueError, match="mismatch"):
        c.acquire("bad", "r3")
    assert c.cache_stats() == before and c.census_ok()
    # the would-be victim survived with content intact
    assert c.resident("good")
    _, up = c.acquire("good", "r4")
    assert not up and int(c.bank[c.slot_of("good")]) == 1


def test_cache_validation():
    store, c = _cache()
    with pytest.raises(KeyError, match="unknown adapter"):
        c.acquire("nope", "r")
    c.acquire("a0", "r")
    with pytest.raises(ValueError, match="already pinned"):
        c.acquire("a0", "r")
    with pytest.raises(ValueError, match="no pin"):
        c.release("a0", "other")
    with pytest.raises(ValueError, match="n_slots"):
        AdapterCache(store, 1, lambda: None, lambda b, s, d: b)
    with pytest.raises(ValueError, match="already registered"):
        store.add("a0", {"salt": 1})
    with pytest.raises(ValueError, match="non-empty"):
        AdapterStore({"": 1})


# --- sim engine: multiplexing ------------------------------------------------

def test_sim_multiplexed_vs_dedicated_parity_and_oracle():
    """One engine mixing 4 adapters (2-usable-slot bank, so the LRU
    churns) produces per-request streams bit-equal to dedicated
    runs AND to the closed-form sim oracle."""
    store = _sim_store(4)
    trace = _zipf(seed=0, n=60)
    res = _sim_engine(lora_slots=3, adapters=store).run(trace)
    assert len(res.outputs) == len(trace)
    assert res.adapter_stats["invariant_ok"]
    assert res.adapter_stats["evictions"] > 0  # the bank DID churn
    sim = make_sim_serving(lora_slots=3)
    for k in range(4):
        sub = [r for r in trace if r.adapter == f"a{k}"]
        dres = _sim_engine(lora_slots=3, adapters=store).run(sub)
        for r in sub:
            a, b = res.outputs[r.rid], dres.outputs[r.rid]
            m = min(len(a), len(b))
            assert a[:m] == b[:m], r.rid
        full = next((r for r in sub if r.cancel_after is None), None)
        if full is not None:
            assert res.outputs[full.rid] == sim.expected_stream(
                full.prompt, full.max_new_tokens,
                adapter_salt=7919 * (k + 1))
    # base rows decode the identity rule
    base = next(r for r in trace if r.adapter is None
                and r.cancel_after is None)
    assert res.outputs[base.rid] == sim.expected_stream(
        base.prompt, base.max_new_tokens)


def test_sim_determinism_and_bank_size_independence():
    """Same trace twice -> identical everything; a tight bank vs a
    roomy bank changes timing (uploads/evictions), never tokens."""
    store = _sim_store(4)
    trace = _zipf(seed=2, n=50)
    r1 = _sim_engine(lora_slots=3, adapters=store).run(trace)
    r2 = _sim_engine(lora_slots=3, adapters=store).run(trace)
    assert r1.outputs == r2.outputs
    assert r1.slot_log == r2.slot_log
    assert r1.decisions == r2.decisions
    assert r1.adapter_stats == r2.adapter_stats
    roomy = _sim_engine(lora_slots=5, adapters=store).run(trace)
    assert roomy.outputs == r1.outputs
    assert roomy.adapter_stats["evictions"] == 0
    assert r1.adapter_stats["uploads"] > roomy.adapter_stats["uploads"]


def test_adapterless_engine_byte_identical():
    """The tentpole identity clause: an engine with adapters=None on
    an adapter-less trace is byte-identical to PR 11 — and an engine
    WITH adapters configured still produces identical outputs/logs
    on that same trace (identity slot 0)."""
    trace = synthesize_trace(seed=5, n_requests=12, vocab_size=509,
                             prompt_len=(4, 12), output_len=(3, 8),
                             churn_frac=0.2)
    plain = _sim_engine().run(trace)
    assert plain.adapter_stats is None      # result shape unchanged
    rep = plain.report()
    assert not any(k.startswith("adapter") for k in rep)
    multi = _sim_engine(lora_slots=3, adapters=_sim_store()).run(trace)
    assert multi.outputs == plain.outputs
    assert multi.slot_log == plain.slot_log
    assert multi.decisions == plain.decisions
    assert multi.metrics.request_rows() == plain.metrics.request_rows()
    # no adapter ever admitted -> the report block stays absent even
    # on the configured engine (the hits>0 convention)
    assert multi.report() == rep
    assert multi.adapter_stats["uploads"] == 0


def test_engine_save_log_no_adapter_fields(tmp_path):
    """An adapter-less run's save_log carries no adapter artifact —
    the byte-identity regression against a PR-11 log format."""
    trace = synthesize_trace(seed=1, n_requests=6, vocab_size=509)
    res = _sim_engine().run(trace)
    p = tmp_path / "log.jsonl"
    res.save_log(str(p))
    body = open(p).read()
    assert "adapter" not in body


def test_engine_validation():
    store = _sim_store(2)
    trace = [Request(rid="q", arrival=0.0, prompt=(1, 2, 3),
                     max_new_tokens=2, adapter="a0")]
    with pytest.raises(ValueError, match="without adapters="):
        _sim_engine(lora_slots=3).run(trace)
    bad = [dataclasses.replace(trace[0], adapter="zz")]
    with pytest.raises(ValueError, match="unknown adapter"):
        _sim_engine(lora_slots=3, adapters=store).run(bad)
    # adapters= without a lora-enabled factory refuses at build
    with pytest.raises(ValueError, match="lora-enabled"):
        _sim_engine(adapters=store)
    # dense policy refuses; routed coerces to paged
    with pytest.raises(ValueError, match="dense"):
        ServingEngine(serving=make_sim_serving(lora_slots=3),
                      slots=4, policy="dense", adapters=store,
                      clock="fixed")
    eng = ServingEngine(serving=make_sim_serving(lora_slots=3),
                        slots=4, policy="routed", adapters=store,
                        clock="fixed")
    assert eng.policy.name == "paged"


def test_upload_paced_on_fixed_clock():
    """Each miss charges one adapter_upload unit; hits are free. Two
    same-adapter requests arriving apart: exactly one upload span in
    the virtual timeline (finish times shift by exactly one unit vs a
    pre-warmed... measured via the metrics block)."""
    store = _sim_store(2)
    from paddle_tpu.inference import BatchingConfig
    trace = [Request(rid="u0", arrival=0.0, prompt=(1, 2, 3, 4),
                     max_new_tokens=2, adapter="a0"),
             Request(rid="u1", arrival=50.0, prompt=(5, 6, 7, 8),
                     max_new_tokens=2, adapter="a0")]
    res = _sim_engine(lora_slots=3, adapters=store,
                      admission=BatchingConfig(max_batch=1)).run(trace)
    rep = res.report()
    assert rep["adapter_requests"] == 2
    assert rep["adapter_uploads"] == 1
    assert rep["adapter_cache_hits"] == 1
    assert rep["adapter_cache_hit_rate"] == 0.5
    # the second request never paid the upload unit: its end-to-end
    # span is exactly one adapter_upload cost shorter for identical
    # work (the charge lands between arrival and admit)
    rows = {r["rid"]: r for r in res.metrics.request_rows()}
    assert rows["u0"]["e2e"] == pytest.approx(rows["u1"]["e2e"] + 1.0)


def test_refusal_requeues_until_release():
    """More distinct in-flight adapters than usable slots: admission
    refuses, requeues, and completes everyone once pins release —
    nothing lost, census conserved."""
    store = _sim_store(4)
    # 4 long-running rows with 4 distinct adapters, bank of 2 usable
    trace = [Request(rid=f"p{k}", arrival=0.0,
                     prompt=tuple(range(1, 5)), max_new_tokens=12,
                     adapter=f"a{k}") for k in range(4)]
    res = _sim_engine(lora_slots=3, adapters=store).run(trace)
    assert len(res.outputs) == 4
    assert all(len(v) == 12 for v in res.outputs.values())
    assert res.adapter_stats["refusals"] > 0
    assert res.adapter_stats["invariant_ok"]


def test_qos_scheduled_loop_and_metrics_gauge():
    """The QoS loop threads adapters too; publish() exports the
    resident gauge only for multi-model runs."""
    store = _sim_store(3)
    trace = _zipf(seed=4, n=30, n_adapters=3)
    res = _sim_engine(lora_slots=4, adapters=store,
                      scheduler=QoSScheduler(max_queue=64)).run(trace)
    assert res.adapter_stats["invariant_ok"]
    rep = res.metrics.publish()
    assert rep["adapter_requests"] > 0
    g = obs_metrics.REGISTRY.gauge("serving_adapter_resident")
    assert g.value >= 0
    # single-model publish never touches the gauge
    plain_trace = synthesize_trace(seed=0, n_requests=4,
                                   vocab_size=509)
    pres = _sim_engine().run(plain_trace)
    rec = pres.metrics.publish()
    assert not any(k.startswith("adapter") for k in rec)


# --- cluster placement -------------------------------------------------------

def _cluster_spawn(store, lora_slots=5):
    def spawn(name):
        return _sim_engine(lora_slots=lora_slots, adapters=store,
                           scheduler=QoSScheduler(max_queue=32))
    return spawn


def test_placement_routes_to_adapter_residency():
    """With the load-slack escape effectively off (huge slack), each
    adapter converges onto one replica: one upload per adapter
    fleet-wide, every later sharer routes to the holder and hits."""
    from paddle_tpu.serving import PrefixAwarePlacement
    store = _sim_store(4)
    trace = _zipf(seed=0, n=200, n_adapters=4, base_frac=0.0,
                  churn_frac=0.0, service_tokens_per_unit=60.0,
                  overload=0.5)
    res = ClusterRouter(
        _cluster_spawn(store), 4,
        placement=PrefixAwarePlacement(
            adapter_load_slack=10 ** 6)).run(trace)
    ups = [res.results[n].adapter_stats["uploads"]
           for n in sorted(res.results)]
    assert sum(ups) == 4
    assert res.census()["conserved"]


def test_placement_replicates_hot_adapter_under_load():
    """One scorching adapter, four replicas: the load-slack rule must
    replicate it instead of drowning the single holder."""
    store = _sim_store(1)
    trace = _zipf(seed=1, n=300, n_adapters=1, base_frac=0.0,
                  churn_frac=0.0, service_tokens_per_unit=12.0,
                  overload=1.6)
    res = ClusterRouter(_cluster_spawn(store), 4,
                        placement="prefix_aware").run(trace)
    holders = sum(1 for n in sorted(res.results)
                  if res.results[n].adapter_stats["uploads"] > 0)
    assert holders >= 2  # replicated beyond the first holder
    assert res.census()["conserved"]


def test_placement_slack_validation():
    from paddle_tpu.serving import PrefixAwarePlacement
    with pytest.raises(ValueError, match="adapter_load_slack"):
        PrefixAwarePlacement(adapter_load_slack=0)


def test_disagg_handoff_moves_adapter_pin():
    """Adapters compose with disaggregated prefill->decode handoffs:
    the prefill worker prefills WITH the adapter and unpins at
    export, the decode worker re-pins (uploading on first sight),
    streams stay bit-equal to a lone multiplexed engine, and both
    stages' slot censuses balance."""
    store = _sim_store(2)
    trace = [Request(rid=f"h{k}", arrival=float(k),
                     prompt=tuple(range(1 + k, 7 + k)),
                     max_new_tokens=4, adapter=f"a{k % 2}")
             for k in range(8)]

    def spawn(name):
        return _sim_engine(lora_slots=3, adapters=store,
                           prefill_chunk_budget=2)
    res = ClusterRouter(spawn, 2, placement="disaggregated",
                        roles={"r0": "prefill", "r1": "decode"},
                        kv_transfer_unit=0.05).run(trace)
    cen = res.census()
    assert cen["conserved"] and cen["pool_census_ok"]
    assert cen["handoffs"]["exported"] == len(trace)
    lone = _sim_engine(lora_slots=3, adapters=store).run(trace)
    outs = res.outputs()
    assert outs == lone.outputs
    for name in ("r0", "r1"):
        ast = res.results[name].adapter_stats
        assert ast["invariant_ok"]
        assert ast["uploads"] == 2       # each stage saw both once
        assert ast["resident_slots"] == 0  # every pin released


# --- real tiny-llama factory -------------------------------------------------

@pytest.fixture(scope="module")
def lora_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


def _real_factory(model, lora=None):
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    return llama_serving_decode_factory(
        model, max_len=48, page_size=8, n_pool_pages=25,
        batch_capacity=4, chunked_prefill=8, lora=lora)


@pytest.fixture(scope="module")
def real_env(lora_model):
    model, cfg = lora_model
    lc = LoRAConfig(n_slots=3, rank=2)
    store = AdapterStore({
        f"a{k}": synthesize_lora_deltas(cfg, 2, seed=k + 1,
                                        init_scale=0.25)
        for k in range(3)})
    return {"model": model, "cfg": cfg, "lc": lc, "store": store,
            "srv": _real_factory(model, lora=lc),
            "srv_plain": _real_factory(model)}


def _real_trace(seed=1, n=10):
    return _zipf(seed=seed, n=n, n_adapters=3, base_frac=0.3,
                 churn_frac=0.0, prompt_len=(5, 12), output_len=(3, 6),
                 vocab_size=VOCAB)


def test_real_multiplexed_vs_dedicated_parity(real_env):
    """The acceptance claim on the real factory: every multiplexed
    stream bit-equal to a dedicated single-adapter engine, and the
    adapters genuinely change tokens."""
    trace = _real_trace()
    eng = ServingEngine(serving=real_env["srv"], slots=4,
                        policy="paged", clock="fixed",
                        adapters=real_env["store"])
    res = eng.run(trace)
    srv2 = _real_factory(real_env["model"], lora=real_env["lc"])
    diverged = 0
    for k in range(3):
        sub = [r for r in trace if r.adapter == f"a{k}"]
        if not sub:
            continue
        ded = ServingEngine(serving=srv2, slots=4, policy="paged",
                            clock="fixed", adapters=real_env["store"])
        dres = ded.run(sub)
        for r in sub:
            assert res.outputs[r.rid] == dres.outputs[r.rid], r.rid
    # vs the BASE model the adapter streams must (mostly) differ —
    # a delta that changes nothing would make parity vacuous
    plain = ServingEngine(serving=real_env["srv_plain"], slots=4,
                          policy="paged", clock="fixed")
    base = plain.run([dataclasses.replace(r, adapter=None)
                      for r in trace])
    for r in trace:
        if r.adapter is not None \
                and res.outputs[r.rid] != base.outputs[r.rid]:
            diverged += 1
    assert diverged > 0
    assert res.adapter_stats["invariant_ok"]


def test_real_decode_never_recompiles_across_adapter_churn(real_env):
    """The recompile acceptance claim: ONE decode_n cache entry
    across adapter mix churn (bank + ids are jit inputs)."""
    trace = _real_trace(seed=2, n=12)
    eng = ServingEngine(serving=real_env["srv"], slots=4,
                        policy="paged", clock="fixed",
                        adapters=real_env["store"])
    eng.run(trace)
    assert eng._p_decode_n._cache_size() == 1
    assert eng._p_decode_n is real_env["srv"].paged_parts[5]


def test_real_adapterless_identity(real_env):
    """adapter=None rows through the identity slot are bit-equal to
    the PLAIN (no-lora) factory — outputs, slot logs, decisions,
    records."""
    trace = [dataclasses.replace(r, adapter=None)
             for r in _real_trace(seed=3, n=8)]
    plain = ServingEngine(serving=real_env["srv_plain"], slots=4,
                          policy="paged", clock="fixed").run(trace)
    multi = ServingEngine(serving=_real_factory(real_env["model"],
                                                lora=real_env["lc"]),
                          slots=4, policy="paged", clock="fixed",
                          adapters=real_env["store"]).run(trace)
    assert multi.outputs == plain.outputs
    assert multi.slot_log == plain.slot_log
    assert multi.decisions == plain.decisions
    assert multi.metrics.request_rows() == plain.metrics.request_rows()
    assert plain.adapter_stats is None


def test_lora_config_and_hooks_validation(real_env):
    assert as_lora_config(None) is None
    assert as_lora_config((4, 2)) == LoRAConfig(n_slots=4, rank=2)
    assert as_lora_config(LoRAConfig(3, 1)).n_slots == 3
    with pytest.raises(ValueError, match="n_slots"):
        LoRAConfig(n_slots=1)
    with pytest.raises(ValueError, match="rank"):
        LoRAConfig(rank=0)
    with pytest.raises(ValueError, match="lora"):
        as_lora_config("wide")
    # delta-shape validation at upload
    import jax.numpy as jnp
    init, upload = lora_bank_hooks(real_env["cfg"], LoRAConfig(3, 2),
                                   jnp.float32)
    bank = init()
    good = synthesize_lora_deltas(real_env["cfg"], 2, seed=9)
    bank = upload(bank, 1, good)
    assert float(abs(bank["q_A"][:, 1]).sum()) > 0
    assert float(abs(bank["q_A"][:, 0]).sum()) == 0  # identity slot
    bad = dict(good)
    bad.pop("v_B")
    with pytest.raises(ValueError, match="missing"):
        upload(bank, 1, bad)
    wrong = dict(good, q_A=good["q_A"][:, :, :1])
    with pytest.raises(ValueError, match="shape"):
        upload(bank, 1, wrong)
    # engine-level lora conflict with a prebuilt factory
    with pytest.raises(ValueError, match="conflicts"):
        ServingEngine(serving=real_env["srv"], slots=4,
                      policy="paged", lora=LoRAConfig(5, 2),
                      adapters=real_env["store"])


def test_real_lora_composes_with_tp(real_env):
    """The docs' TP composition claim: a mesh-sharded factory with a
    replicated adapter bank produces bit-equal multiplexed streams to
    the unsharded engine (the delta add reshards into the
    column-parallel q/v layout under GSPMD)."""
    from paddle_tpu.models.nlp.llama_decode import (
        TPConfig, llama_serving_decode_factory)
    trace = _real_trace(seed=5, n=6)
    srv_tp = llama_serving_decode_factory(
        real_env["model"], max_len=48, page_size=8, n_pool_pages=25,
        batch_capacity=4, chunked_prefill=8, tp=TPConfig((2,)),
        lora=real_env["lc"])
    r1 = ServingEngine(serving=real_env["srv"], slots=4,
                       policy="paged", clock="fixed",
                       adapters=real_env["store"]).run(trace)
    r2 = ServingEngine(serving=srv_tp, slots=4, policy="paged",
                       clock="fixed",
                       adapters=real_env["store"]).run(trace)
    assert r2.outputs == r1.outputs
    assert r2.adapter_stats["invariant_ok"]


# --- trace report ------------------------------------------------------------

def test_trace_report_adapter_rows(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from trace_report import adapter_summary, load_trace as _load
    store = _sim_store(3)
    trace = _zipf(seed=6, n=20, n_adapters=3)
    p = tmp_path / "tr.json"
    res = _sim_engine(lora_slots=4, adapters=store,
                      trace=str(p)).run(trace)
    row = adapter_summary(_load(str(p)))
    assert row is not None and row["bench"] == "trace_report_adapter"
    assert row["adapter_requests"] == sum(
        1 for r in trace if r.adapter is not None)
    assert row["uploads"] == res.adapter_stats["uploads"]
    assert set(row["by_adapter"]) <= {"a0", "a1", "a2"}
    # absence: a single-model trace yields no row at all
    p2 = tmp_path / "tr2.json"
    _sim_engine(trace=str(p2)).run(
        synthesize_trace(seed=0, n_requests=4, vocab_size=509))
    assert adapter_summary(_load(str(p2))) is None


# --- gate family -------------------------------------------------------------

def _gate_rows(ratio=1.5, parity=True, census=True, compared=100,
               drop_arm=None):
    def arm(name):
        return {"bench": "serving_lora", "arm": name, "device": "sim",
                "conserved": True, "pool_census_ok": True,
                "adapter_census_ok": census}
    rows = [arm("multiplexed"), arm("split"),
            {"bench": "serving_lora_summary",
             "multiplexed_vs_split_goodput": ratio,
             "adapters": 4, "replicas": 4, "requests": 1000,
             "adapter_census_ok": census,
             "parity_ok": parity, "parity_compared": compared}]
    if drop_arm:
        rows = [r for r in rows if r.get("arm") != drop_arm]
    return rows


def test_gate_serving_lora_pass_and_fails(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from bench_gate import check_serving_lora

    assert check_serving_lora(_gate_rows()) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["gate"] == "pass"
    assert out["multiplexed_vs_split_goodput"] == 1.5

    for rows, frag in (
            (_gate_rows(ratio=1.1), "floor"),
            (_gate_rows(parity=False), "DIVERGED"),
            (_gate_rows(compared=0), "DIVERGED"),
            (_gate_rows(census=False), "census"),
            (_gate_rows(drop_arm="split"), "BOTH"),
            ([r for r in _gate_rows()
              if r["bench"] != "serving_lora_summary"], "UNVERIFIED")):
        assert check_serving_lora(rows) == 1
        out = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert out["gate"] == "FAIL"
        assert frag in out["reason"]


@pytest.mark.slow
def test_lora_bench_arm_end_to_end(capsys):
    """The --lora arm at reduced size: rows parse, the gate passes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_workload_bench as swb
    from bench_gate import check_serving_lora
    rc = swb.main(["--cpu", "--lora", "--lora-requests", "800"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    arms = {r.get("arm") for r in rows
            if r.get("bench") == "serving_lora"}
    assert arms == {"multiplexed", "split"}
    assert check_serving_lora(rows) == 0
