"""Speculative decoding as an adaptive serving backend.

The claims: eligible rows decode through ONE batched draft/verify
round per turn (draft proposes ``n_draft`` tokens, the target
verifies them in one (k+1)-position block through the paged pool)
with greedy acceptance keeping every emitted token EXACTLY the
target's greedy token — speculation changes latency, never content
(sim AND real tiny-llama factory, TP composed); draft and target
share ONE PagedKVCache page-id space so prefix caching and eviction
recycle both pools in lockstep; the per-request adaptive rule routes
loose-deadline/low-priority traffic speculative and keeps tight
traffic plain; the route falls back deterministically when the
acceptance EWMA sinks below its floor (latched) or while a
page-severity incident delivered through
``QoSScheduler.note_incident`` stays open (released at close), every
flip logged on the virtual clock with its explain rule; ``spec=None``
is byte-identical to the plain engine (outputs, slot logs,
decisions, records, report keys, registry contents); the
metrics/trace spec blocks appear ONLY for spec traffic; and the
``serving_spec`` bench-gate family passes its pass rows and fails
its FAIL rows.
"""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.nlp.llama_decode import (
    SpecConfig, as_spec_config, llama_serving_decode_factory)
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs.slo import BurnRateRule
from paddle_tpu.serving import (Policy, QoSScheduler, Request,
                                ServingEngine, load_trace,
                                make_sim_serving, save_trace,
                                synthesize_deadline_mix_trace,
                                synthesize_recurring_prefix_trace,
                                synthesize_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COSTS = {"prefill_unit": 1.0, "decode": 1.0,
         "spec_decode": 1.25, "spec_prefill": 0.25}


def _sim_engine(spec_accept=None, spec=None, slots=8, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", dict(COSTS))
    kw.setdefault("decode_chunk", 1)
    kw.setdefault("expect_churn", True)
    return ServingEngine(
        serving=make_sim_serving(max_len=64, page_size=8, slots=slots,
                                 vocab=509,
                                 n_pool_pages=slots * 8 + 1 + 16,
                                 spec_accept=spec_accept),
        slots=slots, policy="paged", spec=spec, **kw)


def _churn_trace(seed=0, n=60):
    return synthesize_trace(
        seed=seed, n_requests=n, arrival="poisson",
        mean_interarrival=0.5, prompt_len=(4, 16), output_len=(8, 24),
        vocab_size=509, shared_prefix_frac=0.3, prefix_len=8,
        churn_frac=0.2, rid_prefix="m")


# --- config + eligibility rule ------------------------------------------


def test_spec_config_validation():
    assert as_spec_config(None) is None
    assert as_spec_config(3) == SpecConfig(n_draft=3)
    # bool is checked BEFORE int: spec=True is the stock config, not
    # a degenerate one-token draft window
    assert as_spec_config(True) == SpecConfig()
    assert as_spec_config(False) is None
    cfg = SpecConfig(n_draft=2, accept_floor=0.5)
    assert as_spec_config(cfg) is cfg
    with pytest.raises(ValueError, match="n_draft"):
        SpecConfig(n_draft=0)
    with pytest.raises(ValueError, match="accept_floor"):
        SpecConfig(accept_floor=1.5)
    with pytest.raises(ValueError, match="ewma_alpha"):
        SpecConfig(ewma_alpha=0.0)
    with pytest.raises(ValueError, match="min_rounds"):
        SpecConfig(min_rounds=0)
    with pytest.raises(ValueError, match="loose_deadline_ms"):
        SpecConfig(loose_deadline_ms=-1.0)
    with pytest.raises(ValueError, match="spec"):
        as_spec_config("fast")


def test_spec_route_rule():
    """The per-request adaptive rule: low priority + loose/absent
    deadline -> spec; tight deadline or high priority -> plain, with
    the clause named (the explain discipline)."""
    cfg = SpecConfig()
    pol = Policy()

    def req(priority=0, deadline_ms=None):
        return Request(rid="r", arrival=0.0, prompt=(1, 2, 3),
                       max_new_tokens=4, priority=priority,
                       deadline_ms=deadline_ms)

    ok, rule = pol.spec_route(req(), cfg)
    assert ok and "spec-eligible" in rule
    ok, rule = pol.spec_route(req(deadline_ms=60_000.0), cfg)
    assert ok
    ok, rule = pol.spec_route(req(priority=1), cfg)
    assert not ok and "priority" in rule
    ok, rule = pol.spec_route(req(deadline_ms=2_000.0), cfg)
    assert not ok and "deadline" in rule


# --- sim parity / throughput / determinism ------------------------------


def test_sim_spec_parity_and_speedup():
    """The tentpole claim at sim scale: token-for-token parity with
    plain decode on the mixed churn trace (cancels and prefix hits
    included), spec stats banked, and — at honest fixed pricing —
    MORE tokens per clock unit."""
    trace = _churn_trace()
    plain = _sim_engine().run(trace)
    spec = _sim_engine(spec_accept=0.85,
                       spec=SpecConfig(n_draft=4)).run(trace)
    assert spec.outputs == plain.outputs
    st = spec.spec_stats
    assert st is not None and st["rounds"] > 0
    assert 0.0 < st["acceptance_rate"] <= 1.0
    assert st["draft_tokens_proposed"] > st["draft_tokens_accepted"]
    rp, rs = plain.report(), spec.report()
    assert rs["tokens_per_sec"] > rp["tokens_per_sec"]
    # prefix caching still serves spec admissions
    assert rs.get("prefix_cache_hit_tokens", 0) > 0
    assert spec.cache_stats["invariant_ok"]


def test_sim_spec_deterministic_replay():
    trace = _churn_trace(seed=5, n=40)

    def run():
        return _sim_engine(spec_accept=0.7,
                           spec=SpecConfig(n_draft=4)).run(trace)
    a, b = run(), run()
    assert a.outputs == b.outputs
    assert a.spec_stats == b.spec_stats
    assert a.slot_log == b.slot_log


def test_spec_none_byte_identity():
    """The identity clause: spec=None on a spec-CAPABLE factory is
    byte-identical to the plain factory's engine — outputs, slot
    logs, decisions, records, report keys — and creates none of the
    spec registry metrics."""
    trace = _churn_trace(seed=2, n=24)
    obs_metrics.REGISTRY.reset()
    plain = _sim_engine().run(trace)
    capable = _sim_engine(spec_accept=0.9, spec=None).run(trace)
    assert capable.outputs == plain.outputs
    assert capable.slot_log == plain.slot_log
    assert capable.decisions == plain.decisions
    assert capable.metrics.request_rows() == plain.metrics.request_rows()
    assert capable.spec_stats is None
    rep = capable.report()
    assert json.dumps(rep, sort_keys=True) \
        == json.dumps(plain.report(), sort_keys=True)
    for k in ("spec_rounds", "spec_acceptance_rate",
              "draft_tokens_proposed", "draft_tokens_wasted"):
        assert k not in rep
    names = {key[0] for key in obs_metrics.REGISTRY._metrics}
    assert not any(n.startswith(("serving_spec", "serving_draft"))
                   for n in names)


def test_spec_metrics_block_and_gauges():
    trace = _churn_trace(seed=3, n=24)
    res = _sim_engine(spec_accept=0.8,
                      spec=SpecConfig(n_draft=4)).run(trace)
    rep = res.report()
    assert rep["spec_rounds"] == res.spec_stats["rounds"]
    assert rep["draft_tokens_proposed"] \
        == res.spec_stats["draft_tokens_proposed"]
    assert rep["draft_tokens_wasted"] == (
        res.spec_stats["draft_tokens_proposed"]
        - res.spec_stats["draft_tokens_accepted"])
    assert rep["spec_acceptance_rate"] \
        == res.spec_stats["acceptance_rate"]
    # publish() lands the block as gauges (scalar fields)
    rec = res.metrics.publish()
    g = obs_metrics.REGISTRY.gauge("serving_run_spec_acceptance_rate")
    assert g.value == rec["spec_acceptance_rate"]


# --- the adaptive fallbacks ---------------------------------------------


def test_acceptance_floor_latches_plain():
    """A draft that almost never matches: the EWMA sinks below the
    floor after min_rounds and the route LATCHES plain — flip logged
    with the acceptance rule, no spec rounds after, outputs still
    bit-equal to plain decode."""
    trace = _churn_trace(seed=7, n=40)
    spec = _sim_engine(
        spec_accept=0.0,
        spec=SpecConfig(n_draft=4, accept_floor=0.3, min_rounds=6,
                        ewma_alpha=0.5)).run(trace)
    plain = _sim_engine().run(trace)
    assert spec.outputs == plain.outputs
    st = spec.spec_stats
    assert st["latched"] and not st["enabled_end"]
    assert len(st["flips"]) == 1
    assert "acceptance ewma" in st["flips"][0]["rule"]
    assert st["flips"][0]["enabled"] is False
    # acceptance evidence stops accumulating once latched
    assert st["acceptance_rate"] < 0.3


class _FakeIncident:
    severity = "page"

    def __init__(self):
        self.open = True


def test_scheduler_overload_seam_unit():
    s = QoSScheduler()
    inc = _FakeIncident()
    s.note_incident(inc)           # untracked: not armed
    assert not s.overload_active()
    s.track_overload = True
    inc2 = _FakeIncident()
    s.note_incident(inc2)
    assert s.overload_active()
    inc2.open = False
    assert not s.overload_active()  # closed incidents prune lazily
    # an incident still OPEN at run end must not park the NEXT run:
    # its per-run monitor is gone, so nothing would ever close it —
    # reset() clears the tracking list (the degrade clamp keeps its
    # PR-11 survive-reset semantics)
    s.note_incident(_FakeIncident())
    assert s.overload_active()
    s.reset()
    assert not s.overload_active()


def test_overload_fallback_and_reenable():
    """The declared seam end to end: the deadline-mix surge burns, a
    page-severity BurnRateRule incident lands through
    QoSScheduler.note_incident, the route flips plain; the burn
    recovers, the incident closes, the route re-enables — and spec
    rounds actually RESUME for rows admitted after the clear, while
    no spec round runs inside the parked window (rows caught by the
    flip are demoted — their draft cache went stale on the plain
    turns). Flip timeline deterministic across two seeded replays."""
    from paddle_tpu import obs
    trace = synthesize_deadline_mix_trace(
        seed=0, n_requests=220, service_tokens_per_unit=8.0,
        base_load=0.55, surge=(0.45, 0.2, 5.0), output_len=(6, 16))

    def run(tr=None):
        rule = BurnRateRule(
            name="deadline_burn", objective=0.6,
            windows=((60.0, 1.5), (15.0, 1.5)),
            bad="deadline_missed", min_events=10, severity="page")
        return _sim_engine(
            spec_accept=0.85, spec=SpecConfig(n_draft=4),
            scheduler=QoSScheduler(max_queue=64), slo=[rule],
            trace=tr
        ).run(trace)

    tracer = obs.Tracer()
    res = run(tracer)
    st = res.spec_stats
    downs = [f for f in st["flips"] if not f["enabled"]]
    ups = [f for f in st["flips"] if f["enabled"]]
    assert downs and ups
    assert all("overload" in f["rule"] for f in downs)
    assert all("cleared" in f["rule"] for f in ups)
    assert not st["latched"]
    assert any(i.rule == "deadline_burn" and i.resolution
               == "burn_recovered" for i in res.incidents)
    # spec_decode spans (in-memory tracer ts = virtual clock units):
    # none inside any parked window, some after the final re-enable
    # — rows admitted post-clear genuinely resume the spec route
    spans = sorted(e["ts"] for e in tracer.events
                   if e.get("ph") == "X"
                   and e.get("name") == "spec_decode")
    windows = []
    for d in downs:
        up_after = [u["t"] for u in ups if u["t"] > d["t"]]
        windows.append((d["t"], min(up_after) if up_after
                        else float("inf")))
    assert spans
    for t in spans:
        assert not any(lo < t < hi for lo, hi in windows)
    assert any(t > ups[-1]["t"] for t in spans)
    assert run().spec_stats["flips"] == st["flips"]


def test_mixed_spec_and_plain_rows():
    """Tight/high-priority rows ride the PLAIN group of the same
    engine while loose rows spec — admit instants carry the verdict,
    outputs match a fully plain engine."""
    from paddle_tpu import obs
    base = _churn_trace(seed=9, n=20)
    import dataclasses as dc
    trace = [dc.replace(r, priority=1 if i % 3 == 0 else 0)
             for i, r in enumerate(base)]
    tr = obs.Tracer()
    spec = _sim_engine(spec_accept=0.85, spec=SpecConfig(n_draft=4),
                       trace=tr).run(trace)
    plain = _sim_engine().run(trace)
    assert spec.outputs == plain.outputs
    admits = {e["args"]["rid"]: e["args"]
              for e in tr.events if e.get("ph") == "i"
              and e.get("name") == "admit"}
    for r in trace:
        assert admits[r.rid]["spec"] == (r.priority == 0)
    # plain rows never bank draft evidence
    specs = {e["args"]["rid"] for e in tr.events
             if e.get("ph") == "i" and e.get("name") == "spec"}
    assert all(r.priority == 0 for r in trace if r.rid in specs)
    assert specs  # the loose cohort actually ran spec rounds


def test_spec_trace_instants_absent_on_plain():
    from paddle_tpu import obs
    trace = _churn_trace(seed=4, n=12)
    tr = obs.Tracer()
    _sim_engine(trace=tr).run(trace)
    names = {e.get("name") for e in tr.events}
    assert "spec" not in names and "spec_flip" not in names


# --- sim spec step unit -------------------------------------------------


def test_sim_spec_step_oracle():
    """The sim spec step's emitted tokens ARE the true rule's
    (verified against expected_stream), acceptance counts come from
    real draft-vs-truth comparison, and the pool ends holding the
    true history."""
    sim = make_sim_serving(max_len=64, page_size=8, slots=2,
                           vocab=509, spec_accept=1.0)
    pools = sim.paged_parts[2]
    prefill = sim.paged_parts[3]
    spec_step = sim.spec_parts[4]
    prompt = [5, 9, 13, 17, 21, 25, 29, 33]
    toks = np.asarray([prompt], np.int64)
    pt = np.zeros((1, 8), np.int64)
    pt[0, :2] = [1, 2]
    first, pools = prefill(None, None, toks, pt,
                           np.asarray([8]), pools)
    exp = sim.expected_stream(prompt, 6)
    assert int(first[0]) == exp[0]
    prev = np.asarray([prompt[-1], 0], np.int64)
    tok = np.asarray([exp[0], 0], np.int64)
    bpt = np.zeros((2, 8), np.int64)
    bpt[0] = pt[0]
    lens = np.asarray([8, 0], np.int64)
    counts, cands, pools, _ = spec_step(
        None, None, None, None, prev, tok, bpt, lens, pools,
        None, 4)
    n = int(counts[0])
    assert n == 4  # spec_accept=1.0: every draft matches
    assert [int(x) for x in cands[0][:n + 1]] == exp[1:n + 2]
    # inactive row untouched
    assert int(counts[1]) == 0 and not cands[1].any()


def test_deadline_mix_trace_shape(tmp_path):
    a = synthesize_deadline_mix_trace(seed=11, n_requests=50)
    b = synthesize_deadline_mix_trace(seed=11, n_requests=50)
    assert a == b  # deterministic in every field
    cohorts = {r.rid.rsplit(".", 1)[1] for r in a}
    assert cohorts == {"loose", "tight"}
    for r in a:
        loose = r.rid.endswith(".loose")
        assert r.priority == (0 if loose else 1)
        assert r.deadline_ms is not None
        if loose:
            # loose deadlines clear the default eligibility floor
            assert r.deadline_ms >= SpecConfig().loose_deadline_ms
    p = tmp_path / "mix.jsonl"
    save_trace(str(p), a)
    assert load_trace(str(p)) == a
    with pytest.raises(ValueError, match="surge"):
        synthesize_deadline_mix_trace(surge=(1.5, 0.1, 2.0))
    with pytest.raises(ValueError, match="loose_frac"):
        synthesize_deadline_mix_trace(loose_frac=1.5)


# --- engine construction errors / validation ----------------------------


def test_spec_engine_construction_errors():
    with pytest.raises(ValueError, match="spec-capable"):
        _sim_engine(spec_accept=None, spec=SpecConfig())
    with pytest.raises(ValueError, match="dense"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, spec_accept=0.5),
            slots=4, policy="dense", spec=SpecConfig())
    with pytest.raises(ValueError, match="spec_accept"):
        make_sim_serving(max_len=64, page_size=8, spec_accept=1.5)
    # spec_draft without spec would build a draft stack nothing uses
    with pytest.raises(ValueError, match="spec_draft"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4),
            slots=4, policy="paged", spec_draft=object())


def test_prefill_role_session_skips_draft_walk():
    """A prefill-role session's rows hand off and decode PLAIN on
    the importer — no draft prefill is paid for them (compute the
    fleet could never cash)."""
    from paddle_tpu import obs
    eng = _sim_engine(spec_accept=0.85, spec=SpecConfig(n_draft=4))
    tr = obs.Tracer()
    sess = eng.session(tracer=tr, role="prefill")
    for r in _churn_trace(seed=12, n=4)[:4]:
        sess.advance_until(r.arrival)
        sess.submit(r)
    sess.advance_until(1e6)
    assert sess.handoff_ready  # prefills exported as handoffs
    assert not any(e.get("name") == "spec_prefill"
                   for e in tr.events if e.get("ph") == "X")


def test_spec_footprint_validation():
    """The verify window deepens the page footprint: a request that
    fits plain decode exactly refuses under a wide draft window."""
    eng = _sim_engine(spec_accept=0.5, spec=SpecConfig(n_draft=8),
                      slots=2)
    r = Request(rid="big", arrival=0.0,
                prompt=tuple(range(1, 41)), max_new_tokens=16)
    assert _sim_engine(slots=2)._footprint(r) <= 64  # plain fits
    with pytest.raises(ValueError, match="write slack"):
        eng.run([r])


def test_spec_session_matches_run():
    """EngineSession's incremental drive produces the same streams
    and spec evidence as run() on a spec engine."""
    trace = _churn_trace(seed=6, n=24)
    run_res = _sim_engine(spec_accept=0.8,
                          spec=SpecConfig(n_draft=4)).run(trace)
    eng = _sim_engine(spec_accept=0.8, spec=SpecConfig(n_draft=4))
    sess = eng.session()
    for r in sorted(trace, key=lambda r: (r.arrival, r.rid)):
        sess.advance_until(r.arrival)
        sess.submit(r)
    res = sess.finish()
    assert res.outputs == run_res.outputs
    assert res.spec_stats["draft_tokens_accepted"] \
        == run_res.spec_stats["draft_tokens_accepted"]


# --- real tiny-llama factory --------------------------------------------


@pytest.fixture(scope="module")
def real_env():
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    cfg_d = LlamaConfig.tiny(vocab=97, hidden=16, layers=1, heads=2,
                             kv_heads=1)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    paddle.seed(0)
    twin = LlamaForCausalLM(cfg)   # same seed: a perfect draft
    twin.eval()
    paddle.seed(1)
    draft = LlamaForCausalLM(cfg_d)
    draft.eval()
    return {"cfg": cfg, "model": model, "twin": twin, "draft": draft}


def _real_factory(model, draft=None, tp=None):
    return llama_serving_decode_factory(
        model, max_len=64, page_size=8,
        n_pool_pages=4 * 8 + 1 + 8, batch_capacity=4,
        chunked_prefill=8, draft=draft, tp=tp)


def _real_trace(seed=0, n=8):
    return synthesize_trace(seed=seed, n_requests=n,
                            arrival="poisson", mean_interarrival=0.5,
                            prompt_len=(4, 12), output_len=(4, 10),
                            vocab_size=97, churn_frac=0.2,
                            rid_prefix="q")


def test_real_spec_parity(real_env):
    """The correctness tentpole on the REAL factory: a small
    independent draft proposes mostly-wrong tokens, verification
    rejects them, and every stream is bit-equal to plain decode."""
    trace = _real_trace()
    plain = ServingEngine(serving=_real_factory(real_env["model"]),
                          slots=4, policy="paged",
                          clock="fixed").run(trace)
    spec = ServingEngine(
        serving=_real_factory(real_env["model"],
                              draft=real_env["draft"]),
        slots=4, policy="paged", clock="fixed",
        spec=SpecConfig(n_draft=3, accept_floor=0.0)).run(trace)
    assert spec.outputs == plain.outputs
    assert spec.spec_stats["rounds"] > 0


def test_real_spec_perfect_draft_accepts(real_env):
    """A draft identical to the target must accept every proposal —
    the acceptance arithmetic's positive control."""
    trace = _real_trace(seed=2, n=4)
    spec = ServingEngine(
        serving=_real_factory(real_env["model"],
                              draft=real_env["twin"]),
        slots=4, policy="paged", clock="fixed",
        spec=SpecConfig(n_draft=3)).run(trace)
    assert spec.spec_stats["acceptance_rate"] >= 0.99
    plain = ServingEngine(serving=_real_factory(real_env["model"]),
                          slots=4, policy="paged",
                          clock="fixed").run(trace)
    assert spec.outputs == plain.outputs


def test_real_spec_prefix_cache_shares_chain(real_env):
    """Draft K/V rides the target's page chains: a recurring prefix
    hits for spec rows (round-2 cached tokens > 0 — the TARGET
    prefill skips its cached chunks; the draft re-walks the shared
    chain so its pool is warm no matter who published) and the
    streams stay bit-equal to plain decode."""
    trace = synthesize_recurring_prefix_trace(
        seed=0, n_cohorts=1, cohort_size=3, rounds=2,
        prefix_len=24, tail_len=(2, 6), output_len=(3, 5),
        vocab_size=97, round_gap=80.0)
    spec = ServingEngine(
        serving=_real_factory(real_env["model"],
                              draft=real_env["draft"]),
        slots=4, policy="paged", clock="fixed",
        fixed_costs={"prefill_unit": 1.0, "decode": 1.0},
        spec=SpecConfig(n_draft=3)).run(trace)
    plain = ServingEngine(
        serving=_real_factory(real_env["model"]), slots=4,
        policy="paged", clock="fixed",
        fixed_costs={"prefill_unit": 1.0, "decode": 1.0}).run(trace)
    assert spec.outputs == plain.outputs
    r2 = [rid for rid in spec.prefix_cached if "-r2" in rid]
    assert r2 and any(spec.prefix_cached[rid] > 0 for rid in r2)
    assert spec.cache_stats["invariant_ok"]


def test_real_spec_tp_composition(real_env):
    """TP composes: target sharded on the 2-device mesh, draft
    replicated — streams bit-equal to the unsharded spec engine and
    to plain decode."""
    if len(__import__("jax").devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    trace = _real_trace(seed=3, n=6)
    plain = ServingEngine(serving=_real_factory(real_env["model"]),
                          slots=4, policy="paged",
                          clock="fixed").run(trace)
    tp_spec = ServingEngine(
        serving=_real_factory(real_env["model"],
                              draft=real_env["draft"], tp=2),
        slots=4, policy="paged", clock="fixed",
        spec=SpecConfig(n_draft=3)).run(trace)
    assert tp_spec.outputs == plain.outputs
    assert tp_spec.spec_stats["rounds"] > 0


def test_spec_factory_surface(real_env):
    """Factory surface: spec_parts present with the draft pool on
    the SAME page-id space; the spec step shim advertises its jitted
    program via _jit_inner (the PR-4 compile-observability
    convention), as does the PR-1 compiled spec generate."""
    srv = _real_factory(real_env["model"], draft=real_env["draft"])
    assert srv.spec_parts is not None
    d_pools = srv.spec_parts[2]
    import jax
    leaves = jax.tree_util.tree_leaves(d_pools)
    assert all(a.shape[2] == srv.n_pool_pages_ for a in leaves)
    spec_step = srv.spec_parts[4]
    assert getattr(spec_step, "_jit_inner", None)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_speculative_decode_factory)
    gen = llama_speculative_decode_factory(
        real_env["model"], real_env["twin"], max_len=64, n_draft=2)
    assert getattr(gen.compiled, "_jit_inner", None)
    # vocab mismatch refuses
    cfg_v = LlamaConfig.tiny(vocab=53, hidden=16, layers=1, heads=2,
                             kv_heads=1)
    paddle.seed(2)
    other = LlamaForCausalLM(cfg_v)
    with pytest.raises(ValueError, match="vocabulary"):
        _real_factory(real_env["model"], draft=other)


def test_spec_compile_instants(real_env):
    """The engine's recompile detector sees spec compiles through
    the _jit_inner seam: a cold spec run records jit.compile
    instants at the spec_decode and spec_prefill sites."""
    from paddle_tpu import obs
    tr = obs.Tracer()
    eng = ServingEngine(
        serving=_real_factory(real_env["model"],
                              draft=real_env["draft"]),
        slots=4, policy="paged", clock="fixed", trace=tr,
        spec=SpecConfig(n_draft=3))
    eng.run(_real_trace(seed=5, n=4))
    sites = {e["args"]["site"] for e in tr.events
             if e.get("name") == "jit.compile"}
    assert "spec_decode" in sites
    assert "spec_prefill" in sites


# --- trace_report + gate ------------------------------------------------


def test_trace_report_spec_rows():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from trace_report import report, spec_accepts, spec_summary

    from paddle_tpu import obs
    trace = _churn_trace(seed=8, n=16)
    tr = obs.Tracer()
    _sim_engine(spec_accept=0.8, spec=SpecConfig(n_draft=4),
                trace=tr).run(trace)
    evts = tr.events + [
        {"ph": "M", "name": "thread_name", "tid": t,
         "args": {"name": n}}
        for t, n in getattr(tr, "_tracks", {}).items()]
    # export round-trip is the honest event surface
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t.json")
        tr.export(p)
        with open(p) as f:
            evts = json.load(f)["traceEvents"]
    acc = spec_accepts(evts)
    assert acc and all(v["proposed"] >= v["accepted"] >= 0
                       for v in acc.values())
    row = spec_summary(evts)
    assert row["bench"] == "trace_report_spec"
    assert row["spec_requests"] == len(acc)
    txt = report(evts)
    assert "speculative route" in txt and "accept=" in txt

    # pre-spec trace: no column, no section, no row
    tr2 = obs.Tracer()
    _sim_engine(trace=tr2).run(trace)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t.json")
        tr2.export(p)
        with open(p) as f:
            evts2 = json.load(f)["traceEvents"]
    assert spec_summary(evts2) is None
    txt2 = report(evts2)
    assert "speculative route" not in txt2 and "accept=" not in txt2


def _gate_rows(ratio=1.3, parity=True, compared=360, census=True,
               fallback=1, reenable=1, deterministic=True,
               drop_arm=None):
    rows = [
        {"bench": "serving_spec", "arm": "plain", "device": "sim",
         "tokens_per_sec": 4.6, "census_ok": census},
        {"bench": "serving_spec", "arm": "adaptive_spec",
         "device": "sim", "tokens_per_sec": 4.6 * ratio,
         "census_ok": census},
        {"bench": "serving_spec_overload", "device": "sim",
         "census_ok": census, "fallback_flips": fallback,
         "reenable_flips": reenable,
         "flips_deterministic": deterministic},
        {"bench": "serving_spec_summary", "device": "sim",
         "requests": compared, "n_draft": 4,
         "outputs_match": parity, "parity_compared": compared,
         "spec_vs_plain_tokens_per_sec": ratio,
         "acceptance_rate": 0.66, "fallback_flips": fallback,
         "reenable_flips": reenable,
         "flips_deterministic": deterministic}]
    if drop_arm:
        rows = [r for r in rows if r.get("arm") != drop_arm]
    return rows


def test_gate_serving_spec_pass_and_fails(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from bench_gate import check_serving_spec

    assert check_serving_spec(_gate_rows()) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["gate"] == "pass"
    assert out["spec_vs_plain_tokens_per_sec"] == 1.3

    for rows, frag in (
            (_gate_rows(ratio=0.9), "floor"),
            (_gate_rows(parity=False), "DIVERGED"),
            (_gate_rows(compared=0), "DIVERGED"),
            (_gate_rows(census=False), "census"),
            (_gate_rows(fallback=0), "never flipped"),
            (_gate_rows(reenable=0), "never flipped"),
            (_gate_rows(deterministic=False), "diverged across"),
            (_gate_rows(drop_arm="plain"), "BOTH"),
            ([r for r in _gate_rows()
              if r["bench"] != "serving_spec_overload"],
             "UNVERIFIED"),
            ([r for r in _gate_rows()
              if r["bench"] != "serving_spec_summary"],
             "UNVERIFIED")):
        assert check_serving_spec(rows) == 1
        out = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert out["gate"] == "FAIL"
        assert frag in out["reason"]


@pytest.mark.slow
def test_spec_bench_arm_end_to_end(capsys):
    """The --spec arm at reduced size: rows parse, the gate passes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_workload_bench as swb
    from bench_gate import check_serving_spec
    rc = swb.main(["--cpu", "--spec", "--spec-requests", "160"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    arms = {r.get("arm") for r in rows
            if r.get("bench") == "serving_spec"}
    assert arms == {"plain", "adaptive_spec"}
    assert check_serving_spec(rows) == 0
