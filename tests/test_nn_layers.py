"""nn.Layer + layer zoo tests (~ test_layers.py family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def test_layer_registration():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 3)
            self.w = self.create_parameter((2, 2))
            self.register_buffer("buf", paddle.ones([2]))

        def forward(self, x):
            return self.fc(x)

    m = M()
    names = dict(m.named_parameters())
    assert set(names) == {"w", "fc.weight", "fc.bias"}
    sd = m.state_dict()
    assert "buf" in sd
    assert len(m.parameters()) == 3


def test_state_dict_roundtrip(tmp_path):
    m1 = nn.Linear(4, 3)
    m2 = nn.Linear(4, 3)
    paddle.save(m1.state_dict(), str(tmp_path / "m.pdparams"))
    m2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy())


def test_train_eval_mode():
    m = nn.Sequential(nn.Linear(3, 3), nn.Dropout(0.5))
    assert m.training
    m.eval()
    assert not m[1].training
    m.train()
    assert m[1].training


def test_forward_hooks():
    m = nn.Linear(2, 2)
    calls = []
    h1 = m.register_forward_pre_hook(lambda layer, inp: calls.append("pre"))
    h2 = m.register_forward_post_hook(
        lambda layer, inp, out: calls.append("post"))
    m(paddle.ones([1, 2]))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()
    calls.clear()
    m(paddle.ones([1, 2]))
    assert calls == []


def test_linear_math():
    m = nn.Linear(3, 2)
    x = paddle.ones([4, 3])
    out = m(x)
    expected = x.numpy() @ m.weight.numpy() + m.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)


def test_conv2d_shape_and_grad():
    m = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.to_tensor(np.random.randn(2, 3, 16, 16).astype(np.float32),
                         stop_gradient=False)
    out = m(x)
    assert out.shape == [2, 8, 8, 8]
    out.sum().backward()
    assert m.weight.grad is not None
    assert x.grad.shape == [2, 3, 16, 16]


def test_conv2d_vs_scipy():
    from scipy.signal import correlate2d
    x = np.random.randn(1, 1, 8, 8).astype(np.float32)
    w = np.random.randn(1, 1, 3, 3).astype(np.float32)
    m = nn.Conv2D(1, 1, 3, bias_attr=False)
    m.weight.set_value(w)
    out = m(paddle.to_tensor(x))
    ref = correlate2d(x[0, 0], w[0, 0], mode="valid")
    np.testing.assert_allclose(out.numpy()[0, 0], ref, rtol=1e-4, atol=1e-5)


def test_conv_transpose_inverts_shape():
    m = nn.Conv2DTranspose(4, 3, 3, stride=2, padding=1, output_padding=1)
    x = paddle.randn([2, 4, 8, 8])
    out = m(x)
    assert out.shape == [2, 3, 16, 16]


def test_grouped_depthwise_conv():
    m = nn.Conv2D(8, 8, 3, groups=8, padding=1)
    x = paddle.randn([1, 8, 5, 5])
    assert m(x).shape == [1, 8, 5, 5]


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = F.max_pool2d(x, 2, 2)
    np.testing.assert_allclose(out.numpy()[0, 0], [[5, 7], [13, 15]])
    out = F.avg_pool2d(x, 2, 2)
    np.testing.assert_allclose(out.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    out = F.adaptive_avg_pool2d(x, 1)
    np.testing.assert_allclose(out.numpy()[0, 0], [[7.5]])


def test_batchnorm_stats_update():
    m = nn.BatchNorm2D(3, momentum=0.9)
    x = paddle.to_tensor(
        (np.random.randn(8, 3, 4, 4) * 2 + 5).astype(np.float32))
    m.train()
    out = m(x)
    # output approx standardized
    o = out.numpy()
    assert abs(o.mean()) < 0.1
    assert abs(o.std() - 1.0) < 0.1
    # running stats moved toward batch stats
    assert np.all(m._mean.numpy() > 0.1)
    m.eval()
    out_eval = m(x)
    assert out_eval.shape == [8, 3, 4, 4]


def test_layernorm():
    m = nn.LayerNorm(6)
    x = paddle.to_tensor(np.random.randn(4, 6).astype(np.float32) * 3 + 1)
    out = m(x).numpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)


def test_rmsnorm():
    m = nn.RMSNorm(8)
    x = paddle.randn([2, 8])
    out = m(x).numpy()
    rms = np.sqrt((x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, x.numpy() / rms, rtol=1e-4, atol=1e-5)


def test_groupnorm_instancenorm():
    x = paddle.randn([2, 8, 4, 4])
    gn = nn.GroupNorm(4, 8)
    assert gn(x).shape == [2, 8, 4, 4]
    inorm = nn.InstanceNorm2D(8)
    assert inorm(x).shape == [2, 8, 4, 4]


def test_embedding():
    m = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([[1, 2], [0, 3]], np.int64))
    out = m(ids)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[1, 0], np.zeros(4))
    out.sum().backward()
    g = m.weight.grad.numpy()
    assert np.allclose(g[0], 0)
    assert not np.allclose(g[1], 0)


def test_sequential_and_layerlist():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert m(paddle.ones([1, 4])).shape == [1, 2]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll.parameters())) == 6


def test_losses():
    logits = paddle.to_tensor(np.random.randn(8, 5).astype(np.float32),
                              stop_gradient=False)
    labels = paddle.to_tensor(np.random.randint(0, 5, 8).astype(np.int64))
    loss = F.cross_entropy(logits, labels)
    assert loss.size == 1
    loss.backward()
    assert logits.grad is not None
    # numpy oracle
    x = logits.numpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(8), labels.numpy()]).mean()
    np.testing.assert_allclose(float(loss._value), ref, rtol=1e-5)


def test_cross_entropy_ignore_index_and_soft():
    logits = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32))
    labels = paddle.to_tensor(np.array([0, -100, 2, 1], np.int64))
    loss = F.cross_entropy(logits, labels, ignore_index=-100)
    x = logits.numpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 2, 3], [0, 2, 1]]).mean()
    np.testing.assert_allclose(float(loss._value), ref, rtol=1e-5)
    soft = paddle.to_tensor(np.full((4, 3), 1 / 3, np.float32))
    loss2 = F.cross_entropy(logits, soft, soft_label=True)
    assert loss2.size == 1


def test_bce_mse():
    p = paddle.to_tensor(np.array([0.3, 0.7], np.float32))
    y = paddle.to_tensor(np.array([0.0, 1.0], np.float32))
    ref = -(np.log(0.7) + np.log(0.7)) / 2
    np.testing.assert_allclose(
        float(F.binary_cross_entropy(p, y)._value), ref, rtol=1e-5)
    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([0.0, 0.0], np.float32))
    np.testing.assert_allclose(float(F.mse_loss(a, b)._value), 2.5)


def test_multihead_attention():
    m = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = m(x)
    assert out.shape == [2, 6, 16]


def test_mha_cache_incremental():
    m = nn.MultiHeadAttention(8, 2)
    m.eval()
    x = paddle.randn([1, 4, 8])
    cache = m.gen_cache(x, type=nn.MultiHeadAttention.Cache)
    step = paddle.randn([1, 1, 8])
    out, cache = m(step, step, step, None, cache)
    assert out.shape == [1, 1, 8]
    assert cache.k.shape[1] == 1
    out, cache = m(step, step, step, None, cache)
    assert cache.k.shape[1] == 2


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    out = enc(x)
    assert out.shape == [2, 5, 16]
    # each cloned layer has独立 params
    p0 = enc.layers[0].linear1.weight.numpy()
    p1 = enc.layers[1].linear1.weight.numpy()
    assert p0.shape == p1.shape


def test_full_transformer():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32,
                           dropout=0.0)
    src = paddle.randn([2, 4, 16])
    tgt = paddle.randn([2, 3, 16])
    out = model(src, tgt)
    assert out.shape == [2, 3, 16]


def test_scaled_dot_product_attention_causal():
    q = paddle.randn([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    assert out.shape == [1, 4, 2, 8]
    # causal: first position output depends only on first kv
    q2_np = q.numpy().copy()
    q2_np[:, 1:] = 0
    out2 = F.scaled_dot_product_attention(
        paddle.to_tensor(q.numpy()), paddle.to_tensor(q2_np),
        paddle.to_tensor(q2_np), is_causal=True)
    np.testing.assert_allclose(out.numpy()[:, 0], out2.numpy()[:, 0],
                               rtol=1e-4, atol=1e-5)


def test_lstm_gru():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = paddle.randn([2, 5, 4])
    out, (h, c) = lstm(x)
    assert out.shape == [2, 5, 8]
    assert h.shape == [2, 2, 8]
    gru = nn.GRU(4, 8, direction="bidirect")
    out, h = gru(x)
    assert out.shape == [2, 5, 16]


def test_lstm_cell_grad():
    cell = nn.LSTMCell(3, 4)
    x = paddle.to_tensor(np.random.randn(2, 3).astype(np.float32),
                         stop_gradient=False)
    h, (h2, c2) = cell(x)
    h.sum().backward()
    assert x.grad is not None
    assert cell.weight_ih.grad is not None


def test_interpolate():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = F.interpolate(x, size=[4, 4], mode="nearest")
    assert out.shape == [1, 1, 4, 4]
    out = F.interpolate(x, scale_factor=2, mode="bilinear")
    assert out.shape == [1, 1, 4, 4]


def test_pixel_shuffle():
    x = paddle.randn([1, 8, 2, 2])
    out = F.pixel_shuffle(x, 2)
    assert out.shape == [1, 2, 4, 4]


def test_to_dtype():
    m = nn.Linear(2, 2)
    m.to(dtype="bfloat16")
    assert m.weight.dtype == paddle.bfloat16
