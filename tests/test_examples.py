"""The examples/ recipes stay runnable (subprocess smoke).

Each example is a user-facing contract; run the quick ones end-to-end
the way a user would (fresh process, PYTHONPATH=repo, CPU backend).
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, extra_env=None, timeout=420):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PYTHONSTARTUP", None)
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO)
    assert r.returncode == 0, (name, r.stdout[-800:], r.stderr[-800:])
    return r.stdout


@pytest.mark.parametrize("name,expect", [
    ("train_static_graph.py", "reloaded artifact output"),
    ("serve_predictor.py", "served 8 requests"),
    ("finetune_hapi.py", "predict logits shape: (4, 10)"),
    ("train_ssd_detection.py", "top detection: class 1"),
    ("serve_paged_llama.py", "served 6 requests"),
])
def test_example_runs(name, expect):
    out = _run(name)
    assert expect in out, out[-800:]


def test_example_4d_mesh():
    out = _run("train_llama_4d_mesh.py",
               extra_env={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert "1/8 of the moments" in out, out[-800:]


def test_example_long_context():
    out = _run("train_llama_long_context.py")
    assert "long-context train OK" in out, out[-800:]


def test_example_routed_decode():
    out = _run("serve_routed_decode.py")
    assert "routed serving OK" in out, out[-800:]
    assert "routed -> dense" in out and "routed -> paged" in out


def test_example_window_sep():
    out = _run("train_llama_window_sep.py",
               extra_env={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert "window x sep train OK" in out, out[-800:]
    assert "ring walks 2 of 4 steps" in out, out[-800:]


def test_example_moe_ep():
    out = _run("train_moe_ep.py",
               extra_env={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert "expert shard fraction: 0.250" in out, out[-800:]
    assert "step 7: loss" in out, out[-800:]
