"""Same-host multi-process cluster bring-up test.

~ the reference's TestDistBase pillar (unittests/test_dist_base.py:782 /
test_parallel_dygraph_dataparallel.py:152 run_mnist_2gpu, which shells out
to the launcher itself): spawn real trainer processes via
``python -m paddle_tpu.distributed.launch``, validate the PADDLE_* env
contract, and exchange data cross-process through the C++ TCPStore
rendezvous — the full SURVEY.md §3.5 bring-up path without TPUs.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import os
import subprocess
import sys
import textwrap

import pytest


TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    sys.path.insert(0, "/root/repo")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    rank = int(os.environ["PADDLE_GLOBAL_RANK"])
    world = int(os.environ["PADDLE_WORLD_SIZE"])
    local = int(os.environ["PADDLE_LOCAL_RANK"])
    master = os.environ["PADDLE_MASTER"]
    endpoints = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(endpoints) == world

    # cross-process barrier + KV exchange over the TCPStore rendezvous
    from paddle_tpu.distributed.store import TCPStore
    host, port = master.split(":")
    store = TCPStore(host, int(port) + 17, is_master=(rank == 0),
                     world_size=world)
    store.set(f"hello_{rank}", str(rank * 100))
    # every rank waits for every other rank's key (barrier-by-wait)
    got = {}
    for r in range(world):
        store.wait(f"hello_{r}")
        got[r] = int(store.get(f"hello_{r}"))
    out = {"rank": rank, "world": world, "local": local, "got": got}
    with open(os.path.join(os.environ["TEST_OUT_DIR"],
                           f"rank{rank}.json"), "w") as f:
        json.dump(out, f)
""")


def test_launch_two_ranks_rendezvous(tmp_path):
    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=110)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    import json
    results = {}
    for r in range(2):
        p = tmp_path / f"rank{r}.json"
        assert p.exists(), f"rank {r} wrote no result: {proc.stdout}"
        results[r] = json.loads(p.read_text())
    for r in range(2):
        assert results[r]["world"] == 2
        assert results[r]["got"] == {"0": 0, "1": 100}
