"""Direct-socket PP p2p transport unit tests (VERDICT r3 item 6).

The P2PCommunicator now moves tensors over persistent rank-to-rank
sockets; the TCPStore is rendezvous-only (address exchange + scalar
broadcast). These tests drive two communicators in one process (threads
stand in for stages — the transport is the thing under test; the real
two-process path is exercised by test_pp_multiproc.py)."""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.meta_parallel.pp_utils import (
    P2PCommunicator)
from paddle_tpu.distributed.store import TCPStore


@pytest.fixture()
def pair(free_port):
    # one client per communicator, as in real multi-process use — a
    # TCPStore client connection is not shared across threads
    master = TCPStore("127.0.0.1", free_port, is_master=True,
                      world_size=1)
    sb = TCPStore("127.0.0.1", free_port, is_master=False, world_size=1)
    a = P2PCommunicator(master, 0, prefix="__t__")
    b = P2PCommunicator(sb, 1, prefix="__t__")
    yield a, b
    a.close()
    b.close()


def test_roundtrip_dtypes_and_shapes(pair):
    a, b = pair
    for arr in [np.arange(12, dtype=np.float32).reshape(3, 4),
                np.ones((2, 2, 2), np.float16),
                np.array([[True, False]]),
                np.arange(5, dtype=np.int64)]:
        a.send(arr, 1)
        got = b.recv(0)
        assert got.dtype == arr.dtype and got.shape == arr.shape
        np.testing.assert_array_equal(got, arr)


def test_fifo_per_tag_and_tag_isolation(pair):
    a, b = pair
    # interleave two tags; each tag's stream must stay FIFO and isolated
    for i in range(5):
        a.send(np.full((2,), i, np.float32), 1, tag="act")
        a.send(np.full((3,), 100 + i, np.float32), 1, tag="grad")
    for i in range(5):
        assert b.recv(0, tag="act")[0] == i
    for i in range(5):
        assert b.recv(0, tag="grad")[0] == 100 + i


def test_bidirectional_concurrent(pair):
    a, b = pair
    n = 20
    errs = []

    def pump(src, dst, base):
        try:
            for i in range(n):
                src.send(np.full((256,), base + i, np.float32),
                         dst.stage_id)
                got = src.recv(dst.stage_id)
                assert got[0] == (base ^ 1024) + i
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ta = threading.Thread(target=pump, args=(a, b, 0))
    tb = threading.Thread(target=pump, args=(b, a, 1024))
    ta.start()
    tb.start()
    ta.join(60)
    tb.join(60)
    assert not errs, errs


def test_recv_timeout_is_diagnostic(pair, monkeypatch):
    import paddle_tpu.distributed.fleet.meta_parallel.pp_utils.\
        p2p_communication as p2p
    monkeypatch.setattr(p2p, "_RECV_TIMEOUT_S", 0.2)
    a, b = pair
    with pytest.raises(TimeoutError, match="stage 0"):
        b.recv(0, tag="never_sent")


def test_bcast_scalar(pair):
    a, b = pair
    out = []
    t = threading.Thread(
        target=lambda: out.append(b.bcast_scalar(None, src_stage=0)))
    t.start()
    assert a.bcast_scalar(3.25, src_stage=0) == 3.25
    t.join(30)
    assert out == [3.25]


def test_partial_send_recv(pair):
    """PP x TP boundary protocol: each mp rank ships 1/mp of the tensor;
    the receiver reassembles (~ _partial_send/_partial_allgather)."""
    a, b = pair
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    for r in range(2):  # both "mp ranks" share stage 0's communicator
        a.send_partial(x, 1, mp_degree=2, mp_rank=r)
    got = b.recv_partial(0, mp_degree=2, shape=x.shape)
    np.testing.assert_array_equal(got, x)
    with pytest.raises(ValueError, match="not divisible"):
        a.send_partial(np.zeros(7, np.float32), 1, mp_degree=2, mp_rank=0)


def test_sub_rank_columnwise_p2p(free_port):
    """PP x TP: each mp rank runs its OWN communicator per stage; p2p is
    column-wise (same sub_rank), so two mp ranks at one stage no longer
    overwrite each other's listener address."""
    master = TCPStore("127.0.0.1", free_port, is_master=True, world_size=1)
    clients = [TCPStore("127.0.0.1", free_port, is_master=False,
                        world_size=1) for _ in range(3)]
    comms = {}
    for stage in (0, 1):
        for sub in (0, 1):
            st = master if (stage, sub) == (0, 0) else clients.pop()
            comms[(stage, sub)] = P2PCommunicator(
                st, stage, prefix="__col__", sub_rank=sub)
    x = np.arange(8, dtype=np.float32)
    try:
        # stage 0's two mp ranks each send their half down their column
        comms[(0, 0)].send_partial(x, 1, mp_degree=2, mp_rank=0)
        comms[(0, 1)].send_partial(x, 1, mp_degree=2, mp_rank=1)
        got0 = comms[(1, 0)].recv(0, tag="act/p0")
        got1 = comms[(1, 1)].recv(0, tag="act/p1")
        np.testing.assert_array_equal(np.concatenate([got0, got1]), x)
    finally:
        for c in comms.values():
            c.close()
