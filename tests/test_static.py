"""Static-graph mode: capture, Executor, minimize, grads, inference export.

Mirrors the reference's static tests (e.g. test_executor_and_use_program_cache,
test_optimizer, fluid/tests/unittests/test_static_save_load.py) — SURVEY.md
§3.3 stack rebuilt as DAG capture + jax.jit (paddle_tpu/static/).
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


class TestCapture:
    def test_data_and_shapes(self, static_mode):
        with static.program_guard(static.Program(), static.Program()):
            x = static.data("x", [-1, 4], "float32")
            assert x.shape == [-1, 4]
            y = x * 2.0 + 1.0
            assert y.shape == [-1, 4]
            assert y.dtype == np.float32
            r = paddle.sum(y, axis=1)
            assert r.shape == [-1]

    def test_static_var_has_no_value(self, static_mode):
        with static.program_guard(static.Program(), static.Program()):
            x = static.data("x", [2, 2], "float32")
            with pytest.raises(RuntimeError):
                x.numpy()

    def test_program_repr_and_vars(self, static_mode):
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [2, 3], "float32")
            h = static.nn.fc(x, 5)
        assert prog.has_var("x")
        assert len(prog.all_parameters()) == 2  # W, b
        assert prog.var("x") is x
        assert h.shape == [2, 5]


class TestExecutor:
    def test_forward_matches_numpy(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 3], "float32")
            h = static.nn.fc(x, 2)
        exe = static.Executor()
        exe.run(startup)
        W = np.asarray(main._params[0]._value)
        b = np.asarray(main._params[1]._value)
        xs = np.random.default_rng(0).normal(size=(5, 3)).astype("float32")
        hv, = exe.run(main, feed={"x": xs}, fetch_list=[h])
        np.testing.assert_allclose(hv, xs @ W + b, atol=1e-5)

    def test_recompiles_per_batch_size(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 4], "float32")
            s = paddle.sum(x)
        exe = static.Executor()
        for bs in (2, 7, 2):
            xs = np.ones((bs, 4), "float32")
            sv, = exe.run(main, feed={"x": xs}, fetch_list=[s])
            assert float(sv) == pytest.approx(bs * 4.0)

    def test_fetch_by_name_and_tensor(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 2], "float32")
            y = x + 1.0
        exe = static.Executor()
        xs = np.zeros((2, 2), "float32")
        a, b = exe.run(main, feed={"x": xs}, fetch_list=[y, y.name])
        np.testing.assert_allclose(a, b)

    def test_bad_feed_key_raises(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 2], "float32")
            y = x + 1.0
        exe = static.Executor()
        with pytest.raises(KeyError):
            exe.run(main, feed={"nope": np.zeros((2, 2), "f4")},
                    fetch_list=[y])


class TestTraining:
    def test_sgd_minimize_converges(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 4], "float32")
            y = static.data("y", [-1, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(0)
        W = rng.normal(size=(4, 1)).astype("float32")
        xs = rng.normal(size=(64, 4)).astype("float32")
        ys = xs @ W
        first = last = None
        for _ in range(50):
            lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            first = float(lv) if first is None else first
            last = float(lv)
        assert last < first * 0.01

    def test_static_matches_eager_training(self, static_mode):
        """One Adam step on identical params/grads: static vs eager parity
        (the OpTest static-vs-dygraph pillar, SURVEY.md §4)."""
        rng = np.random.default_rng(3)
        W0 = rng.normal(size=(3, 2)).astype("float32")
        xs = rng.normal(size=(6, 3)).astype("float32")
        ys = rng.normal(size=(6, 2)).astype("float32")

        # static
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [6, 3], "float32")
            y = static.data("y", [6, 2], "float32")
            lin = nn.Linear(3, 2, bias_attr=False)
            lin.weight.set_value(W0)
            loss = paddle.mean((lin(x) - y) ** 2)
            optimizer.Adam(learning_rate=0.01,
                           parameters=lin.parameters()).minimize(loss)
        exe = static.Executor()
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        W_static = np.asarray(lin.weight._value)

        # eager
        paddle.disable_static()
        lin2 = nn.Linear(3, 2, bias_attr=False)
        lin2.weight.set_value(W0)
        opt2 = optimizer.Adam(learning_rate=0.01,
                              parameters=lin2.parameters())
        out = lin2(paddle.to_tensor(xs))
        loss2 = paddle.mean((out - paddle.to_tensor(ys)) ** 2)
        loss2.backward()
        opt2.step()
        paddle.enable_static()

        assert float(lv) == pytest.approx(float(loss2.numpy()), abs=1e-5)
        np.testing.assert_allclose(W_static, np.asarray(lin2.weight._value),
                                   atol=1e-5)

    def test_startup_reinitializes(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 2], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean(pred ** 2)
            optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        p = main._params[0]
        w_init = np.asarray(p._value).copy()
        xs = np.random.default_rng(0).normal(size=(4, 2)).astype("float32")
        exe.run(main, feed={"x": xs}, fetch_list=[loss])
        assert not np.allclose(np.asarray(p._value), w_init)
        exe.run(startup)  # restore
        np.testing.assert_allclose(np.asarray(p._value), w_init)


class TestGradients:
    def test_append_backward_numeric(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 3], "float32")
            h = static.nn.fc(x, 2)
            loss = paddle.sum(h * h)
            pg = static.append_backward(loss)
        exe = static.Executor()
        xs = np.random.default_rng(1).normal(size=(8, 3)).astype("float32")
        (p, gvar) = pg[0]
        _, gv = exe.run(main, feed={"x": xs}, fetch_list=[loss, gvar])
        W = np.asarray(p._value)
        b = np.asarray(main._params[1]._value)
        ref = 2 * xs.T @ (xs @ W + b)
        np.testing.assert_allclose(gv, ref, atol=1e-4)

    def test_gradients_wrt_data(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 3], "float32")
            h = static.nn.fc(x, 2)
            loss = paddle.sum(h * h)
            gx, = static.gradients(loss, [x])
        exe = static.Executor()
        xs = np.random.default_rng(1).normal(size=(8, 3)).astype("float32")
        gxv, = exe.run(main, feed={"x": xs}, fetch_list=[gx])
        W = np.asarray(main._params[0]._value)
        b = np.asarray(main._params[1]._value)
        np.testing.assert_allclose(gxv, 2 * (xs @ W + b) @ W.T, atol=1e-4)


class TestInferenceIO:
    def test_save_load_inference_model(self, static_mode, tmp_path):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 3], "float32")
            h = static.nn.fc(x, 2)
        exe = static.Executor()
        path = os.path.join(str(tmp_path), "m")
        static.save_inference_model(path, [x], [h], exe, program=main)
        assert os.path.exists(path + ".pdmodel")
        assert os.path.exists(path + ".pdexport")
        layer, feeds, fetches = static.load_inference_model(path, exe)
        assert feeds == ["x"]
        xs = np.random.default_rng(0).normal(size=(4, 3)).astype("float32")
        out = layer(xs)
        out0 = out[0] if isinstance(out, (list, tuple)) else out
        W = np.asarray(main._params[0]._value)
        b = np.asarray(main._params[1]._value)
        np.testing.assert_allclose(np.asarray(out0.numpy()), xs @ W + b,
                                   atol=1e-5)

    def test_dynamic_batch_export(self, static_mode, tmp_path):
        """-1 feed dims export shape-polymorphically: one artifact
        serves any batch size (same contract as jit.save)."""
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 3], "float32")
            h = static.nn.fc(x, 2)
        exe = static.Executor()
        path = os.path.join(str(tmp_path), "poly")
        static.save_inference_model(path, [x], [h], exe, program=main)
        layer, _, _ = static.load_inference_model(path, exe)
        W = np.asarray(main._params[0]._value)
        b = np.asarray(main._params[1]._value)
        for n in (1, 4, 7):
            xs = np.random.default_rng(n).normal(size=(n, 3)).astype(
                "float32")
            out = layer(xs)
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            np.testing.assert_allclose(np.asarray(out0.numpy()),
                                       xs @ W + b, atol=1e-5)


class TestStaticNN:
    def test_conv_bn_pipeline(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            im = static.data("im", [-1, 3, 8, 8], "float32")
            c = static.nn.conv2d(im, 4, 3, padding=1, act="relu")
            b = static.nn.batch_norm(c)
            pooled = paddle.mean(b)
        exe = static.Executor()
        exe.run(startup)
        ims = np.random.default_rng(2).normal(
            size=(2, 3, 8, 8)).astype("float32")
        pv, = exe.run(main, feed={"im": ims}, fetch_list=[pooled])
        assert np.isfinite(pv).all()

    def test_embedding(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ids = static.data("ids", [-1, 5], "int64")
            emb = static.nn.embedding(ids, size=[10, 4])
        exe = static.Executor()
        idv = np.array([[1, 2, 3, 4, 5]], dtype=np.int64)
        ev, = exe.run(main, feed={"ids": idv}, fetch_list=[emb])
        table = np.asarray(main._params[0]._value)
        np.testing.assert_allclose(ev[0], table[idv[0]], atol=1e-6)


class TestModeSwitch:
    def test_mode_flags(self):
        assert paddle.in_dynamic_mode()
        paddle.enable_static()
        try:
            assert not paddle.in_dynamic_mode()
        finally:
            paddle.disable_static()
        assert paddle.in_dynamic_mode()
