"""Fleet data generators (MultiSlot protocol).

~ reference test_data_generator.py: subclass generate_sample, render the
MultiSlot text protocol, parse back.
"""
import io

from paddle_tpu.distributed.fleet.data_generator import (
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator)


class _G(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def it():
            a, b = line.split(",")
            yield [("ids", [int(a), int(a) + 1]), ("label", [int(b)])]
        return it


class TestMultiSlot:
    def test_protocol_lines(self):
        g = _G()
        g.set_batch(2)
        lines = g.run_from_memory(["1,0", "5,1", "9,0"])
        assert lines == ["2 1 2 1 0", "2 5 6 1 1", "2 9 10 1 0"]

    def test_to_arrays_roundtrip(self):
        g = _G()
        recs = DataGenerator.to_arrays(g.run_from_memory(["3,1"]))
        assert recs[0]["slot_0"].tolist() == [3, 4]
        assert recs[0]["slot_1"].tolist() == [1]

    def test_float_slots(self):
        class F(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("x", [0.5, 1.5])]
                return it

        recs = DataGenerator.to_arrays(F().run_from_memory([None]))
        assert recs[0]["slot_0"].dtype.kind == "f"

    def test_string_generator(self):
        class S(MultiSlotStringDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("w", ["7", "8"])]
                return it

        assert S().run_from_memory([None]) == ["2 7 8"]

    def test_stdin_driver(self, monkeypatch, capsys):
        g = _G()
        g.set_batch(1)
        monkeypatch.setattr("sys.stdin", io.StringIO("2,1\n4,0\n"))
        g.run_from_stdin()
        out = capsys.readouterr().out.strip().split("\n")
        assert out == ["2 2 3 1 1", "2 4 5 1 0"]
