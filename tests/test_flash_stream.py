"""Grid-streamed flash-attention kernels (long-sequence fallback).

The resident-KV flash design hit a Mosaic scoped-VMEM overflow on chip
at S=8192 (21M > 16M) — invisible to interpret mode, which skips VMEM
accounting. The fix is a VMEM fit model in `_resolve_blocks` plus
K/V-streaming kernel variants (online-softmax state in VMEM scratch
across an innermost kv grid dimension) for sequences past the resident
frontier. These tests pin (a) bit-exact equivalence of the streamed
kernels against the resident ones in interpret mode, and (b) the
resolver's mode/block decisions across the S range.

~ reference fused attention: fused_attention_op.cu materializes O(s^2)
scores and cannot reach these lengths at all.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import (
    _resolve_blocks, flash_attention)


def _qkv(B=2, H=3, S=256, D=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((B, H, S, D)),
                             jnp.float32) for _ in range(3))


class TestStreamedEquivalence:
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_resident(self, causal):
        q, k, v = _qkv()
        res = flash_attention(q, k, v, causal, None, 128, 128,
                              None, None, False)
        str_ = flash_attention(q, k, v, causal, None, 128, 128,
                               None, None, True)
        np.testing.assert_array_equal(np.asarray(res), np.asarray(str_))

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_resident(self, causal):
        q, k, v = _qkv()

        def loss(mode):
            def f(q, k, v):
                return flash_attention(q, k, v, causal, None, 128, 128,
                                       None, None, mode).sum()
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        for a, b in zip(loss(False), loss(True)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rectangular_blocks_and_seqs(self):
        # Sq != Sk and block_q != block_k exercise the index maps
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.standard_normal((1, 2, 256, 64)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 512, 64)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 512, 64)), jnp.float32)
        res = flash_attention(q, k, v, False, None, 128, 256,
                              None, None, False)
        str_ = flash_attention(q, k, v, False, None, 128, 256,
                               None, None, True)
        np.testing.assert_array_equal(np.asarray(res), np.asarray(str_))

    def test_streamed_matches_dense_oracle(self):
        q, k, v = _qkv(S=128)
        out = flash_attention(q, k, v, True, None, 64, 64, None, None,
                              True)
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((128, 128), bool))
        s = jnp.where(mask, s, -jnp.inf)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


class TestResolverDecisions:
    def test_short_seq_stays_resident_512(self):
        assert _resolve_blocks(2048, 2048, None, None, 128, 2) == \
            (512, 512, False)

    def test_long_seq_fwd_resident_bwd_streams(self):
        # chip facts (long8k_vmem_repro, 2026-08-01): at S=8192 the
        # FORWARD compiles resident even at 512x512, while the backward
        # (dk/dv holds full-length Q/dO bf16 + f32 compute copies) fails
        # at any block size — 17.00M @512, 16.50M @256 — so bwd streams.
        assert _resolve_blocks(8192, 8192, None, None, 128, 2) == \
            (512, 512, False)
        _, _, streamed = _resolve_blocks(8192, 8192, None, None, 128, 2,
                                         bwd=True)
        assert streamed

    def test_very_long_seq_streams(self):
        for S in (16384, 32768, 131072):
            bq, bk, streamed = _resolve_blocks(S, S, None, None, 128, 2)
            assert streamed, S
            assert S % bq == 0 and S % bk == 0

    def test_explicit_blocks_honored(self):
        bq, bk, _ = _resolve_blocks(8192, 8192, 512, 512, 128, 2)
        assert (bq, bk) == (512, 512)

    def test_stream_forced_off_keeps_resident(self):
        _, _, streamed = _resolve_blocks(32768, 32768, None, None, 128, 2,
                                         stream=False)
        assert not streamed

    def test_stream_forced_on(self):
        _, _, streamed = _resolve_blocks(2048, 2048, None, None, 128, 2,
                                         stream=True)
        assert streamed

    def test_odd_seq_falls_back_to_divisor_blocks(self):
        bq, bk, streamed = _resolve_blocks(96, 96, None, None, 64, 4)
        assert 96 % bq == 0 and 96 % bk == 0 and not streamed

    def test_stream_forced_on_odd_seq_stays_streamed(self):
        # forcing stream must never silently hand back resident kernels,
        # even when no 128-multiple pair divides the sequence
        bq, bk, streamed = _resolve_blocks(96, 96, None, None, 64, 4,
                                           stream=True)
        assert streamed and 96 % bq == 0 and 96 % bk == 0

    def test_partial_explicit_block_honored_under_stream(self):
        bq, bk, streamed = _resolve_blocks(2048, 2048, 256, None, 128, 2,
                                           stream=True)
        assert streamed and bq == 256 and 2048 % bk == 0

    def test_streamed_rejects_non_dividing_blocks(self):
        q, k, v = _qkv(S=192)
        with pytest.raises(ValueError, match="must divide"):
            flash_attention(q, k, v, False, None, 128, 128, None, None,
                            True)

    def test_odd_long_seq_streams_when_resident_cannot_fit(self):
        # odd does not imply tiny: S=16392 divides only into <=128 blocks
        # but resident K/V alone (4*S*D*2 bytes) exceeds the 16M budget
        bq, bk, streamed = _resolve_blocks(16392, 16392, None, None,
                                           128, 2)
        assert streamed and 16392 % bq == 0 and 16392 % bk == 0

    def test_bwd_resident_term_covers_long_sq_short_sk(self):
        # the dk/dv kernel holds Q+dO resident at Sq: a long-Sq/short-Sk
        # gradient must not pick resident mode just because Sk is small
        bq, bk, streamed = _resolve_blocks(32768, 1024, None, None,
                                           128, 2, bwd=True)
        assert streamed
        # the forward of the same shapes holds only K/V (Sk) resident
        _, _, streamed_fwd = _resolve_blocks(32768, 1024, None, None,
                                             128, 2, bwd=False)
        assert not streamed_fwd


class TestAutoStreamEndToEnd:
    def test_auto_pick_runs_streamed_when_resident_cannot_fit(
            self, monkeypatch):
        # simulate the long-context regime (resident K/V over budget)
        # without allocating long-context arrays on CPU
        import importlib
        # the package re-exports the flash_attention FUNCTION under the
        # same name, shadowing dotted-attribute module access
        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        monkeypatch.setattr(fa, "_resident_fits",
                            lambda *a, **k: False)
        bq, bk, streamed = fa._resolve_blocks(512, 512, None, None, 64, 4)
        assert streamed
        q, k, v = _qkv(S=512)
        # auto CAUSAL streaming routes via splash-tril (dead-block DMA
        # elided in fwd/dq); force splash's own STREAMED kernels too —
        # that is the path real S>=16k causal traffic hits
        sp = importlib.import_module(
            "paddle_tpu.ops.pallas.splash_attention")
        monkeypatch.setattr(sp, "_FORCE_STREAM", True)
        out = flash_attention(q, k, v, True)
        ref = flash_attention(q, k, v, True, None, bq, bk, None, None,
                              False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        g_route = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, True).sum(), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, True, None, bq, bk, None, None, False).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_route, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5)
        monkeypatch.setattr(sp, "_FORCE_STREAM", None)
        # auto NON-causal streaming keeps the plain streamed kernels:
        # same blocks as the forced-mode call -> bit-exact
        out_nc = flash_attention(q, k, v, False)
        ref_nc = flash_attention(q, k, v, False, None, bq, bk, None,
                                 None, True)
        np.testing.assert_array_equal(np.asarray(out_nc),
                                      np.asarray(ref_nc))
