"""SSD/RPN detection ops vs hand-computed oracles.

~ fluid/layers/detection.py (prior_box, anchor_generator, box_coder,
iou_similarity, box_clip, multiclass_nms) and unittests
test_prior_box_op.py / test_box_coder_op.py / test_multiclass_nms_op.py.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.detection import (anchor_generator, box_clip,
                                         box_coder, iou_similarity,
                                         multiclass_nms, prior_box)


def test_iou_similarity():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    y = np.array([[0, 0, 2, 2], [10, 10, 12, 12]], np.float32)
    iou = iou_similarity(x, y).numpy()
    np.testing.assert_allclose(iou[0], [1.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(iou[1, 0], 1.0 / 7.0, atol=1e-6)


def test_box_clip():
    boxes = np.array([[-5, -5, 20, 20], [2, 3, 4, 5]], np.float32)
    out = box_clip(boxes, np.array([10.0, 8.0, 1.0])).numpy()
    np.testing.assert_allclose(out[0], [0, 0, 7, 9])  # W-1=7, H-1=9
    np.testing.assert_allclose(out[1], [2, 3, 4, 5])
    # scale: network input 20x16 at scale 2 -> original 10x8 extent
    out = box_clip(boxes, np.array([20.0, 16.0, 2.0])).numpy()
    np.testing.assert_allclose(out[0], [0, 0, 7, 9])


def test_multiclass_nms_unnormalized_iou():
    """normalized=False counts the boundary pixel in IoU (reference
    multiclass_nms_op): two abutting 2-px boxes overlap by 1/3 then."""
    boxes = np.array([[[0, 0, 1, 1], [1, 0, 2, 1]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]
    # normalized: IoU = 0 -> both kept
    _, counts = multiclass_nms(boxes, scores, score_threshold=0.1,
                               nms_threshold=0.3)
    assert int(counts.numpy()[0]) == 2
    # unnormalized: IoU = 2/6 = 0.33 > 0.3 -> second suppressed
    _, counts = multiclass_nms(boxes, scores, score_threshold=0.1,
                               nms_threshold=0.3, normalized=False)
    assert int(counts.numpy()[0]) == 1


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 4, 4], [2, 2, 8, 10]], np.float32)
    pvar = np.array([[0.1, 0.1, 0.2, 0.2]], np.float32)
    targets = np.array([[1, 1, 5, 5], [0, 0, 6, 8]], np.float32)
    enc = box_coder(priors, pvar, targets, "encode_center_size").numpy()
    assert enc.shape == (2, 2, 4)
    # decode(encode(x)) == x, per prior column
    dec = box_coder(priors, pvar, enc, "decode_center_size").numpy()
    for j in range(2):
        np.testing.assert_allclose(dec[:, j], targets, rtol=1e-4,
                                   atol=1e-4)
    # hand oracle for target 0 vs prior 0 (no variance)
    e = box_coder(priors, None, targets, "encode_center_size").numpy()
    # prior0: c=(2,2) wh=(4,4); target0: c=(3,3) wh=(4,4)
    np.testing.assert_allclose(e[0, 0], [0.25, 0.25, 0.0, 0.0],
                               atol=1e-6)


def test_prior_box_shapes_and_values():
    fm = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 8, 8), np.float32)
    boxes, var = prior_box(fm, img, min_sizes=[4.0], max_sizes=[6.0],
                           aspect_ratios=[2.0], clip=True)
    # priors: ar1 + ar2 + sqrt(min*max) = 3
    assert boxes.shape == [2, 2, 3, 4]
    b = boxes.numpy()
    # first cell center = (0.5*4, 0.5*4) = (2,2); ar=1 prior is
    # 4x4 px -> normalized [0, 0, 0.5, 0.5]
    np.testing.assert_allclose(b[0, 0, 0], [0, 0, 0.5, 0.5], atol=1e-6)
    assert (b >= 0).all() and (b <= 1).all()  # clip
    np.testing.assert_allclose(var.numpy()[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_density_prior_box():
    from paddle_tpu.vision.detection import density_prior_box
    fm = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 8, 8), np.float32)
    boxes, var = density_prior_box(fm, img, densities=[2, 1],
                                   fixed_sizes=[2.0, 4.0])
    # P = 2*2 (density 2) + 1 (density 1) = 5 per cell
    assert boxes.shape == [2, 2, 5, 4]
    b = boxes.numpy()
    # density-2 sub-grid: centers at cell_center +- step/4 (step=4 -> +-1)
    # first entry of cell (0,0): center (2-1, 2-1)=(1,1), 2x2 box
    np.testing.assert_allclose(b[0, 0, 0] * 8, [0, 0, 2, 2], atol=1e-5)
    # density-1 entry: centered at (2,2), 4x4 box
    np.testing.assert_allclose(b[0, 0, 4] * 8, [0, 0, 4, 4], atol=1e-5)


def test_anchor_generator_centers():
    fm = np.zeros((1, 8, 2, 3), np.float32)
    anchors, var = anchor_generator(fm, anchor_sizes=[32.0],
                                    aspect_ratios=[1.0],
                                    stride=[16.0, 16.0])
    assert anchors.shape == [2, 3, 1, 4]
    a = anchors.numpy()
    # reference convention (anchor_generator_op.h): center at
    # idx*stride + offset*(stride-1) = 7.5, corners at +/-0.5*(w-1)
    # with base_w = round(sqrt(256/1)) = 16 scaled by 32/16 -> w = 32
    np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 23, 23])
    # x stride moves the center by 16
    np.testing.assert_allclose(a[0, 1, 0], [8, -8, 39, 23])


def test_locality_aware_nms_merges():
    from paddle_tpu.vision.detection import locality_aware_nms
    # two near-duplicate boxes MERGE (weighted average, scores add)
    boxes = np.array([[[0, 0, 4, 4], [0, 0, 4.2, 4.2],
                       [10, 10, 14, 14]]], np.float32)
    scores = np.array([[[0.6, 0.6, 0.5]]], np.float32)  # C=1
    out, cnt = locality_aware_nms(boxes, scores, score_threshold=0.1,
                                  nms_threshold=0.5, keep_top_k=5)
    c = int(cnt.numpy()[0])
    assert c == 2
    o = out.numpy()[0]
    # merged box: equal weights -> midpoint corners, score 1.2
    assert abs(o[0, 1] - 1.2) < 1e-5
    np.testing.assert_allclose(o[0, 2:], [0, 0, 4.1, 4.1], atol=1e-5)
    np.testing.assert_allclose(o[1, 2:], [10, 10, 14, 14])
    # score_threshold applies to the ACCUMULATED post-merge scores
    # (locality_aware_nms_op.cc): the merged pair's 1.2 beats 0.9 and
    # survives; the lone 0.5 box is dropped
    out0, cnt0 = locality_aware_nms(boxes, scores, score_threshold=0.9,
                                    keep_top_k=5)
    assert int(cnt0.numpy()[0]) == 1
    assert abs(out0.numpy()[0, 0, 1] - 1.2) < 1e-5


def test_matrix_nms_decay_and_jit():
    import jax

    from paddle_tpu.vision.detection import matrix_nms
    boxes = np.array([[[0, 0, 4, 4], [0, 0, 4.1, 4.1],
                       [10, 10, 14, 14]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out, cnt = matrix_nms(boxes, scores, score_threshold=0.1,
                          post_threshold=0.3, keep_top_k=5)
    o = np.asarray(out.numpy())[0]
    c = int(cnt.numpy()[0])
    # top box undecayed; heavy-overlap second box decayed below 0.3;
    # disjoint third box survives (decay 1.0)
    assert abs(o[0, 1] - 0.9) < 1e-6 and o[0, 0] == 1
    kept_scores = o[:c, 1]
    assert 0.7 in np.round(kept_scores, 4)
    assert c == 2, (c, o[:, :2])
    # the TPU claim: the whole thing jits (no host-side loop)
    f = jax.jit(lambda b, s: matrix_nms(
        b, s, 0.1, post_threshold=0.3, keep_top_k=5)[0]._value)
    np.testing.assert_allclose(np.asarray(f(boxes, scores))[0], o,
                               rtol=1e-6)


def test_matrix_nms_gaussian():
    from paddle_tpu.vision.detection import matrix_nms
    boxes = np.array([[[0, 0, 4, 4], [0, 0, 4.05, 4.05]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]
    _, cnt_lin = matrix_nms(boxes, scores, 0.1, post_threshold=0.5,
                            keep_top_k=4)
    _, cnt_g = matrix_nms(boxes, scores, 0.1, post_threshold=0.5,
                          keep_top_k=4, use_gaussian=True,
                          gaussian_sigma=0.1)
    # both decay the duplicate below 0.5; gaussian with tiny sigma is
    # at least as aggressive
    assert int(cnt_lin.numpy()[0]) == 1
    assert int(cnt_g.numpy()[0]) == 1


def test_multiclass_nms_padded():
    # 1 image, 2 classes (0 = background), 4 boxes
    boxes = np.array([[[0, 0, 4, 4], [0, 0, 4.1, 4.1],
                       [10, 10, 14, 14], [20, 20, 22, 22]]], np.float32)
    scores = np.zeros((1, 2, 4), np.float32)
    scores[0, 1] = [0.9, 0.85, 0.8, 0.05]
    out, counts = multiclass_nms(boxes, scores, score_threshold=0.1,
                                 nms_threshold=0.5, keep_top_k=10)
    assert out.shape == [1, 10, 6]
    assert int(counts.numpy()[0]) == 2  # overlap suppressed, 0.05 cut
    o = out.numpy()[0]
    assert o[0, 0] == 1 and abs(o[0, 1] - 0.9) < 1e-6
    np.testing.assert_allclose(o[1, 2:], [10, 10, 14, 14])
    assert (o[2:, 0] == -1).all()  # padding rows


def test_detection_map():
    from paddle_tpu.vision.detection import detection_map
    # image 1: one gt of class 1, detected perfectly + one false positive
    det1 = np.array([[1, 0.9, 0, 0, 4, 4],
                     [1, 0.3, 20, 20, 24, 24],
                     [-1, -1, -1, -1, -1, -1]], np.float32)
    gt1 = np.array([[0, 0, 4, 4]], np.float32)
    gl1 = np.array([1], np.int64)
    # perfect single detection: integral AP = 1.0 regardless of the FP
    # at lower score? precision at recall 1.0 is 1/1 -> then FP adds
    # (1.0, 0.5) point after full recall: AP stays 1.0
    m = detection_map([det1], [gt1], [gl1], class_num=2)
    assert abs(m - 1.0) < 1e-6, m
    # missed gt halves recall: two images, second gt undetected
    m2 = detection_map([det1, np.zeros((0, 6), np.float32)],
                       [gt1, gt1], [gl1, gl1], class_num=2)
    assert 0.4 < m2 < 0.6, m2
    # 11-point variant runs and is bounded
    m3 = detection_map([det1], [gt1], [gl1], class_num=2,
                       ap_version="11point")
    assert 0.9 < m3 <= 1.0


def test_polygon_box_transform():
    from paddle_tpu.vision.detection import polygon_box_transform
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 8, 3, 4)).astype(np.float32)
    out = polygon_box_transform(paddle.to_tensor(x)).numpy()
    # oracle straight from polygon_box_transform_op.cc
    ref = np.empty_like(x)
    for c in range(8):
        for h in range(3):
            for w in range(4):
                ref[:, c, h, w] = (w * 4 - x[:, c, h, w] if c % 2 == 0
                                   else h * 4 - x[:, c, h, w])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_bipartite_match():
    from paddle_tpu.vision.detection import bipartite_match
    d = np.array([[0.9, 0.1, 0.6],
                  [0.2, 0.8, 0.7]], np.float32)
    mi, md = bipartite_match(d)
    np.testing.assert_array_equal(mi.numpy(), [0, 1, -1])
    np.testing.assert_allclose(md.numpy(), [0.9, 0.8, 0.0])
    # per_prediction: prior 2's best gt (1, 0.7) clears the threshold
    mi2, md2 = bipartite_match(d, "per_prediction", 0.5)
    np.testing.assert_array_equal(mi2.numpy(), [0, 1, 1])
    np.testing.assert_allclose(md2.numpy(), [0.9, 0.8, 0.7])


def test_target_assign():
    from paddle_tpu.vision.detection import target_assign
    gt = np.array([[1, 2], [3, 4]], np.float32)
    out, w = target_assign(gt, np.array([1, -1, 0], np.int32))
    np.testing.assert_allclose(out.numpy(), [[3, 4], [0, 0], [1, 2]])
    np.testing.assert_allclose(w.numpy()[:, 0], [1, 0, 1])


def test_ssd_loss_learns():
    """The full multibox loss trains a toy head toward the targets."""
    from paddle_tpu.vision.detection import anchor_generator, ssd_loss
    paddle.seed(0)
    fm = np.zeros((1, 8, 4, 4), np.float32)
    priors, _ = anchor_generator(fm, anchor_sizes=[8.0],
                                 aspect_ratios=[1.0], stride=[8.0, 8.0])
    priors = priors.numpy().reshape(-1, 4)
    P = len(priors)
    gt_box = np.array([[6, 6, 14, 14]], np.float32)  # near one anchor
    gt_label = np.array([1], np.int64)
    from paddle_tpu import nn

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.loc = self.create_parameter(
                [P, 4], default_initializer=nn.initializer.Constant(0.0))
            self.conf = self.create_parameter(
                [P, 3], default_initializer=nn.initializer.Constant(0.0))

    head = Head()
    opt = paddle.optimizer.Adam(parameters=head.parameters(),
                                learning_rate=0.1)
    first = None
    for _ in range(15):
        loss = ssd_loss(head.loc, head.conf, gt_box, gt_label, priors)
        if first is None:
            first = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first * 0.5, (first, float(loss))


def test_box_decoder_and_assign():
    from paddle_tpu.vision.detection import box_decoder_and_assign
    priors = np.array([[0, 0, 9, 9]], np.float32)  # w=h=10 (+1 conv)
    pv = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    # class 0 (bg) zero offsets; class 1 shifts center by +1 in x
    t = np.zeros((1, 8), np.float32)
    t[0, 4] = 1.0  # dx for class 1
    scores = np.array([[0.3, 0.7]], np.float32)
    dec, assign = box_decoder_and_assign(priors, pv, t, scores,
                                         box_clip=4.135)
    d = dec.numpy().reshape(1, 2, 4)
    # class 0 decodes back to the prior
    np.testing.assert_allclose(d[0, 0], [0, 0, 9, 9], atol=1e-5)
    # class 1: center (5,5) -> (5 + 0.1*1*10, 5) = (6,5), same size
    np.testing.assert_allclose(d[0, 1], [1, 0, 10, 9], atol=1e-5)
    # assign picks best fg class (1)
    np.testing.assert_allclose(assign.numpy()[0], d[0, 1], atol=1e-5)
    # the reference has NO score floor (box_decoder_and_assign_op.h:77-97):
    # the best non-background class's decoded box is assigned whenever
    # class_num > 1, even for confident-background rois
    _, a2 = box_decoder_and_assign(priors, pv, t,
                                   np.array([[1.0, 0.005]], np.float32),
                                   box_clip=4.135)
    np.testing.assert_allclose(a2.numpy()[0], d[0, 1], atol=1e-5)


def test_generate_proposals():
    from paddle_tpu.vision.detection import (anchor_generator,
                                             generate_proposals)
    rng = np.random.default_rng(0)
    H = W = 4
    A = 2
    fm = np.zeros((1, 8, H, W), np.float32)
    anchors, var = anchor_generator(fm, anchor_sizes=[16.0],
                                    aspect_ratios=[1.0, 2.0],
                                    stride=[8.0, 8.0])
    scores = rng.uniform(0, 1, (1, A, H, W)).astype(np.float32)
    deltas = np.zeros((1, 4 * A, H, W), np.float32)  # boxes = anchors
    rois, n = generate_proposals(scores, deltas,
                                 np.array([[32.0, 32.0, 1.0]]),
                                 anchors, var, post_nms_top_n=10,
                                 nms_thresh=0.7, min_size=1.0)
    assert rois.shape == [1, 10, 4]
    cnt = int(n.numpy()[0])
    assert 0 < cnt <= 10
    r = rois.numpy()[0, :cnt]
    # clipped to the 32x32 input
    assert (r >= 0).all() and (r <= 31).all()
    # rows beyond the count are zero padding
    assert (rois.numpy()[0, cnt:] == 0).all()


def test_collect_fpn_proposals():
    from paddle_tpu.vision.detection import collect_fpn_proposals
    r1 = np.array([[0, 0, 4, 4], [1, 1, 5, 5]], np.float32)
    r2 = np.array([[2, 2, 6, 6]], np.float32)
    s1 = np.array([0.3, 0.9], np.float32)
    s2 = np.array([0.5], np.float32)
    rois, sc = collect_fpn_proposals([r1, r2], [s1, s2],
                                     post_nms_top_n=2)
    np.testing.assert_allclose(sc.numpy(), [0.9, 0.5])
    np.testing.assert_allclose(rois.numpy()[0], [1, 1, 5, 5])
    import pytest as _pytest
    with _pytest.raises(ValueError, match="rois vs"):
        collect_fpn_proposals([r1], [s2], post_nms_top_n=2)


def test_distribute_fpn_proposals_restore():
    from paddle_tpu.vision.detection import distribute_fpn_proposals
    rois = np.array([[0, 0, 10, 10],      # sqrt(area)=10 -> low level
                     [0, 0, 200, 200],    # 200 -> high level
                     [0, 0, 50, 50]], np.float32)
    outs, restore = distribute_fpn_proposals(rois, 2, 5, 4, 224)
    assert len(outs) == 4
    # levels: 10px,50px -> clamp to 2; 200px -> floor(4+log2(200/224))=3
    sizes = [len(o.numpy()) for o in outs]
    assert sizes == [2, 1, 0, 0]
    # restore maps concatenated per-level order back to input order
    cat = np.concatenate([o.numpy() for o in outs])
    np.testing.assert_allclose(cat[restore.numpy()], rois)


def test_rpn_target_assign():
    from paddle_tpu.vision.detection import (anchor_generator,
                                             rpn_target_assign)
    fm = np.zeros((1, 8, 4, 4), np.float32)
    anchors, var = anchor_generator(fm, anchor_sizes=[8.0],
                                    aspect_ratios=[1.0],
                                    stride=[8.0, 8.0])
    an = anchors.numpy().reshape(-1, 4)
    av = var.numpy().reshape(-1, 4)
    gt = np.array([[4, 4, 12, 12]], np.float32)  # ~ anchor 0 region
    loc_idx, score_idx, tgt_bbox, tgt_label = rpn_target_assign(
        an, av, gt, np.array([32.0, 32.0, 1.0]),
        rpn_batch_size_per_im=8, use_random=False)
    fg = loc_idx.numpy()
    assert len(fg) >= 1                      # gt's best anchor is fg
    assert tgt_bbox.shape[0] == len(fg)
    lab = tgt_label.numpy()
    assert set(np.unique(lab)) <= {0, 1}
    assert (lab[:len(fg)] == 1).all()
    assert len(score_idx.numpy()) == len(lab) <= 8
    # no gt: every inside anchor becomes a negative candidate
    _, si, tb, tl = rpn_target_assign(
        an, av, np.zeros((0, 4), np.float32),
        np.array([32.0, 32.0, 1.0]), rpn_batch_size_per_im=8,
        use_random=False)
    assert tb.shape[0] == 0 and (tl.numpy() == 0).all()


def test_generate_proposal_labels():
    from paddle_tpu.vision.detection import generate_proposal_labels
    rois = np.array([[4, 4, 12, 12],    # overlaps gt heavily
                     [20, 20, 28, 28],  # background
                     [5, 5, 13, 13]], np.float32)
    gt_boxes = np.array([[4, 4, 12, 12]], np.float32)
    gt_classes = np.array([3], np.int64)
    out_rois, labels, targets, inw, outw = generate_proposal_labels(
        rois, gt_classes, gt_boxes, np.array([32.0, 32.0, 1.0]),
        batch_size_per_im=4, fg_fraction=0.5, class_nums=5,
        use_random=False)
    np.testing.assert_allclose(outw.numpy(), inw.numpy())
    lab = labels.numpy()
    # fg rows (deterministic with use_random=False: rois 0,2 + the
    # joined gt box, capped at fg_fraction) carry the gt class
    assert (lab[:2] == 3).all()
    assert (lab == 0).sum() >= 1
    t = targets.numpy()
    w = inw.numpy()
    for r in range(len(lab)):
        if lab[r] > 0:
            sl = slice(4 * lab[r], 4 * lab[r] + 4)
            assert (w[r, sl] == 1).all()      # class-slot weights set
            assert w[r].sum() == 4
        else:
            assert w[r].sum() == 0            # bg: no box loss
    assert out_rois.shape[1] == 4
    # im_scale != 1: rois (network-input coords) map back to original-
    # image coords before IoU vs gt — same fg as the scale-1 case
    _, lab2, _, _, _ = generate_proposal_labels(
        rois * 2.0, gt_classes, gt_boxes, np.array([64.0, 64.0, 2.0]),
        batch_size_per_im=4, fg_fraction=0.5, class_nums=5,
        use_random=False)
    assert (lab2.numpy() == 3).sum() == (lab == 3).sum()


def test_multi_box_head():
    from paddle_tpu.vision.detection import MultiBoxHead, ssd_loss
    paddle.seed(0)
    head = MultiBoxHead(num_classes=3, min_sizes=[4.0, 8.0],
                        max_sizes=[8.0, 16.0],
                        aspect_ratios=[[2.0], [2.0]],
                        in_channels=[8, 16], flip=True)
    img = paddle.randn([2, 3, 32, 32])
    f1 = paddle.randn([2, 8, 8, 8])
    f2 = paddle.randn([2, 16, 4, 4])
    locs, confs, priors, var = head([f1, f2], img)
    # priors per cell: 1 + 2 (ar 2 + flip) + 1 (sqrt min*max) = 4
    P = 8 * 8 * 4 + 4 * 4 * 4
    assert locs.shape == [2, P, 4]
    assert confs.shape == [2, P, 3]
    assert priors.shape == [P, 4] and var.shape == [P, 4]
    # the head output feeds ssd_loss directly (prior order matches)
    gt = np.array([[4, 4, 12, 12]], np.float32)
    loss = ssd_loss(locs[0], confs[0], gt, np.array([1], np.int64),
                    priors.numpy() * 32)
    assert np.isfinite(float(loss))
    assert len(head.parameters()) == 8  # 2 maps x (loc+conf) x (w+b)
    # real nn.Layer: registers under a parent model
    from paddle_tpu import nn

    class Parent(nn.Layer):
        def __init__(self):
            super().__init__()
            self.head = head

    assert len(Parent().parameters()) == 8
    # second call with the same shapes hits the prior cache
    head([f1, f2], img)
    assert len(head._prior_cache) == 2


def test_detection_output_ssd_inference():
    from paddle_tpu.vision.detection import detection_output
    priors = np.array([[0, 0, 8, 8], [8, 8, 16, 16]], np.float32)
    loc = np.zeros((2, 4), np.float32)          # boxes = priors
    scores = np.array([[0.05, 0.95], [0.9, 0.1]], np.float32)
    out, cnt = detection_output(loc, scores, priors, None,
                                score_threshold=0.3, keep_top_k=4)
    assert out.shape == [4, 6]
    assert int(cnt.numpy()) == 1                # only prior 0 is fg
    o = out.numpy()
    assert o[0, 0] == 1 and abs(o[0, 1] - 0.95) < 1e-6
    np.testing.assert_allclose(o[0, 2:], [0, 0, 8, 8])


def test_retinanet_target_assign():
    from paddle_tpu.vision.detection import (anchor_generator,
                                             retinanet_target_assign)
    fm = np.zeros((1, 8, 4, 4), np.float32)
    anchors, var = anchor_generator(fm, anchor_sizes=[8.0],
                                    aspect_ratios=[1.0],
                                    stride=[8.0, 8.0])
    an, av = anchors.numpy().reshape(-1, 4), var.numpy().reshape(-1, 4)
    gt = np.array([[3, 3, 13, 13]], np.float32)
    gl = np.array([7], np.int64)
    fg, si, tb, tl = retinanet_target_assign(
        an, av, gt, gl, np.array([32.0, 32.0, 1.0]))
    lab = tl.numpy()
    nf = len(fg.numpy())
    assert nf >= 1 and (lab[:nf] == 7).all()   # per-class fg labels
    # NO subsampling: every below-negative-overlap anchor is kept as bg
    # (the [0.4, 0.5) ignore band is excluded by design)
    from paddle_tpu.vision.detection import iou_similarity
    iou = iou_similarity(gt, an, box_normalized=False).numpy().max(0)
    # forced positives (per-gt best anchors) can sit below 0.4; the
    # rest of the below-negative-overlap anchors are ALL kept as bg
    assert (lab == 0).sum() == (iou < 0.4).sum() - nf
    assert len(lab) == (iou < 0.4).sum()  # = nf + bg, no subsampling


def test_retinanet_detection_output():
    from paddle_tpu.vision.detection import retinanet_detection_output
    # two levels; level-0 anchor 0 is a confident class-1 detection
    anchors = [np.array([[0, 0, 8, 8], [8, 8, 16, 16]], np.float32),
               np.array([[0, 0, 16, 16]], np.float32)]
    deltas = [np.zeros((2, 4), np.float32),
              np.zeros((1, 4), np.float32)]
    scores = [np.array([[0.01, 0.9], [0.02, 0.03]], np.float32),
              np.array([[0.01, 0.6]], np.float32)]
    out, cnt = retinanet_detection_output(
        deltas, scores, anchors, np.array([32.0, 32.0, 1.0]),
        score_threshold=0.05, keep_top_k=5, nms_threshold=0.5)
    assert out.shape == [5, 6]
    c = int(cnt.numpy())
    assert c == 2  # 0.9 and 0.6 survive; 0.01/0.02/0.03 cut
    o = out.numpy()
    assert o[0, 0] == 1 and abs(o[0, 1] - 0.9) < 1e-6
    np.testing.assert_allclose(o[0, 2:], [0, 0, 8, 8])
    assert (o[c:, 0] == -1).all()


def test_multiclass_nms_batch_and_topk():
    rng = np.random.default_rng(0)
    boxes = np.broadcast_to(
        rng.uniform(0, 10, (1, 8, 4)).astype(np.float32),
        (2, 8, 4)).copy()
    boxes[..., 2:] = boxes[..., :2] + 1.0  # valid 1x1 boxes
    scores = rng.uniform(0.2, 1.0, (2, 3, 8)).astype(np.float32)
    out, counts = multiclass_nms(boxes, scores, keep_top_k=3,
                                 score_threshold=0.1)
    assert out.shape == [2, 3, 6]
    assert (counts.numpy() <= 3).all() and (counts.numpy() > 0).all()
    # rows sorted by score within each image
    for n in range(2):
        s = out.numpy()[n, :counts.numpy()[n], 1]
        assert (np.diff(s) <= 1e-6).all()
