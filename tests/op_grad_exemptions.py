"""Explicit numeric-grad exemption table (~ the reference's
unittests/white_list/ pattern, op_test.py check_grad discipline).

Every op registered in ``paddle_tpu.ops.dispatch.OP_REGISTRY`` must be
either numerically grad-swept (tests/test_op_grad_sweep.py +
tests/test_op_grad_sweep_full.py) or listed here with the reason it is
not finite-difference checkable. test_op_grad_sweep_full.py asserts the
partition is exhaustive, so a newly registered differentiable op fails
CI until it is swept or consciously exempted.
"""

EXEMPT = {
    # --- no gradient by type: boolean/integer/index outputs ---------------
    "all": "boolean reduction",
    "allclose": "boolean output",
    "any": "boolean reduction",
    "argmax": "integer index output",
    "argmin": "integer index output",
    "argsort": "integer index output",
    "bincount": "integer histogram output",
    "bitwise_and": "integer/bool bitwise",
    "bitwise_not": "integer/bool bitwise",
    "bitwise_or": "integer/bool bitwise",
    "bitwise_xor": "integer/bool bitwise",
    "count_nonzero": "integer count output",
    "equal": "boolean comparison",
    "greater_equal": "boolean comparison",
    "greater_than": "boolean comparison",
    "isclose": "boolean output",
    "less_equal": "boolean comparison",
    "less_than": "boolean comparison",
    "logical_and": "boolean logic",
    "logical_not": "boolean logic",
    "logical_or": "boolean logic",
    "logical_xor": "boolean logic",
    "matrix_rank": "integer rank output",
    "nonzero": "integer index output",
    "not_equal": "boolean comparison",
    "searchsorted": "integer index output",
    "histogram": "integer counts output",
    "left_shift": "integer bit op",
    "right_shift": "integer bit op",
    "gcd": "integer arithmetic",
    "lcm": "integer arithmetic",
    "floor_divide": "integer-valued output, zero grad a.e.",
    "mod": "piecewise-constant in divisor; fmod grad covered by "
           "identity regions of floor_mod being exercised eagerly",
    "floor_mod": "kinked at every multiple of the divisor; grad wrt x "
                 "is 1 a.e. and covered by frac",
    "one_hot": "integer input, constant output",
    "full_like": "no differentiable input",
    # --- zero gradient almost everywhere ----------------------------------
    "ceil": "zero grad a.e. (staircase)",
    "floor": "zero grad a.e. (staircase)",
    "round": "zero grad a.e. (staircase)",
    "trunc": "zero grad a.e. (staircase)",
    "sign": "zero grad a.e.",
    "heaviside": "zero grad a.e. in x; y-grad only on the null set x=0",
    # --- randomness / sampling --------------------------------------------
    "gumbel_softmax": "stochastic op: output depends on internal gumbel "
                      "noise, FD across two calls measures noise not grad "
                      "(determinism of the relaxation is tested in "
                      "test_ops_phase4)",
    # --- complex-valued domain --------------------------------------------
    # FD on R^n can't probe holomorphic/anti-holomorphic structure; the
    # real-input entry points (rfft/irfft composites) ARE swept in
    # test_op_grad_sweep_full.py; these are their complex-domain kin.
    "fft": "complex output; eager tape carries real cotangents only "
           "(forward parity in the fft op tests)",
    "fft2": "complex output (see fft)",
    "fftn": "complex output (see fft)",
    "rfft": "complex output (see fft)",
    "rfft2": "complex output (see fft)",
    "rfftn": "complex output (see fft)",
    "imag": "zero gradient on the real line",
    "ifft": "complex input/output",
    "ifft2": "complex input/output",
    "ifftn": "complex input/output",
    "hfft": "complex input",
    "hfft2": "complex input",
    "hfftn": "complex input",
    "ihfft": "complex output",
    "ihfft2": "complex output",
    "ihfftn": "complex output",
    "irfft": "complex input (see fft)",
    "irfft2": "complex input",
    "irfftn": "complex input",
    "as_complex": "complex output (linear repack)",
    "as_real": "complex input (linear repack)",
    "complex": "complex output (linear combine)",
    "conj": "complex domain (identity on reals)",
    "angle": "zero/undefined grad on the real line",
    "fftfreq": "no differentiable input (index generator)",
    "rfftfreq": "no differentiable input (index generator)",
    "fftshift": "pure permutation swept via roll",
    "ifftshift": "pure permutation swept via roll",
    # --- gradient lives on a constrained manifold -------------------------
    "cholesky": "jax VJP assumes symmetric input (symmetrized grad); "
                "elementwise FD breaks symmetry",
    "cholesky_solve": "same symmetric-manifold caveat as cholesky",
    "eigvalsh": "symmetric-manifold gradient, FD breaks symmetry",
    # --- non-smooth by construction ---------------------------------------
    "frexp": "mantissa/exponent decomposition is discontinuous",
    "nextafter": "ULP step function, zero grad a.e.",
    "unique": "set-valued output with data-dependent shape",
    "mode": "plateau selection: FD perturbation can flip the modal "
            "bucket; value-path covered by kthvalue/median sweeps",
    # --- not ops over float arrays ----------------------------------------
    "cast": "dtype conversion (identity grad when float->float, "
            "exercised throughout the suite)",
}
