"""Op tests with numpy oracles + numeric grad checks (~ OpTest pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops

from op_test import check_grad, check_output


class TestElementwise:
    def test_add(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        check_output(ops.add, [a, b], a + b)
        check_grad(ops.add, [a, b])

    def test_broadcast_add_grad(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4).astype(np.float32)
        check_output(ops.add, [a, b], a + b)
        check_grad(ops.add, [a, b])

    def test_mul_div(self):
        a = np.random.rand(2, 3).astype(np.float32) + 1
        b = np.random.rand(2, 3).astype(np.float32) + 1
        check_output(ops.multiply, [a, b], a * b)
        check_grad(ops.multiply, [a, b])
        check_output(ops.divide, [a, b], a / b)
        check_grad(ops.divide, [a, b])

    def test_pow_exp_log(self):
        a = np.random.rand(3, 3).astype(np.float32) + 0.5
        check_output(ops.exp, [a], np.exp(a))
        check_grad(ops.exp, [a])
        check_output(ops.log, [a], np.log(a))
        check_grad(ops.log, [a])
        check_output(ops.sqrt, [a], np.sqrt(a))
        check_grad(ops.sqrt, [a])

    def test_trig(self):
        a = np.random.randn(8).astype(np.float32)
        check_output(ops.sin, [a], np.sin(a))
        check_output(ops.cos, [a], np.cos(a))
        check_grad(ops.tanh, [a])

    def test_clip(self):
        a = np.random.randn(10).astype(np.float32)
        check_output(ops.clip, [a], np.clip(a, -0.5, 0.5),
                     attrs={"min": -0.5, "max": 0.5})

    def test_where(self):
        c = np.array([True, False, True])
        a = np.ones(3, np.float32)
        b = np.zeros(3, np.float32)
        check_output(ops.where, [c, a, b], np.where(c, a, b))

    def test_comparison(self):
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([2.0, 2.0, 2.0], np.float32)
        check_output(ops.greater_than, [a, b], a > b)
        check_output(ops.equal, [a, b], a == b)


class TestReduction:
    def test_sum_mean(self):
        a = np.random.randn(3, 4, 5).astype(np.float32)
        check_output(ops.sum, [a], a.sum())
        check_output(ops.sum, [a], a.sum(1), attrs={"axis": 1})
        check_output(ops.mean, [a], a.mean((0, 2)), attrs={"axis": [0, 2]})
        check_grad(ops.mean, [np.random.randn(3, 4).astype(np.float32)],
                   attrs={"axis": 1})

    def test_max_min_grad(self):
        a = np.random.randn(4, 5).astype(np.float32)
        check_output(ops.max, [a], a.max(1), attrs={"axis": 1})
        check_output(ops.min, [a], a.min())

    def test_argmax(self):
        a = np.random.randn(4, 5).astype(np.float32)
        check_output(ops.argmax, [a], a.argmax(1), attrs={"axis": 1})

    def test_std_var(self):
        a = np.random.randn(6, 7).astype(np.float32)
        check_output(ops.std, [a], a.std(ddof=1), rtol=1e-4)
        check_output(ops.var, [a], a.var(0, ddof=1), attrs={"axis": 0},
                     rtol=1e-4)

    def test_logsumexp(self):
        from scipy.special import logsumexp as sp_lse
        a = np.random.randn(4, 6).astype(np.float32)
        check_output(ops.logsumexp, [a], sp_lse(a, axis=1),
                     attrs={"axis": 1}, rtol=1e-4)
        check_grad(ops.logsumexp, [a], attrs={"axis": 1})

    def test_cumsum(self):
        a = np.random.randn(3, 4).astype(np.float32)
        check_output(ops.cumsum, [a], a.cumsum(1), attrs={"axis": 1})
        check_grad(ops.cumsum, [a], attrs={"axis": 0})


class TestManipulation:
    def test_reshape_transpose(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        check_output(ops.reshape, [a], a.reshape(6, 4),
                     attrs={"shape": [6, 4]})
        check_grad(ops.reshape, [a], attrs={"shape": [24]})
        check_output(ops.transpose, [a], a.transpose(2, 0, 1),
                     attrs={"perm": [2, 0, 1]})
        check_grad(ops.transpose, [a], attrs={"perm": [1, 0, 2]})

    def test_concat_stack(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(2, 3).astype(np.float32)
        out = ops.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b]))
        out = ops.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.stack([a, b], 1))

    def test_concat_grad(self):
        a = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
        out = ops.concat([a, b], axis=0)
        (out * 2).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), 2 * np.ones((2, 2)))
        np.testing.assert_allclose(b.grad.numpy(), 2 * np.ones((3, 2)))

    def test_split_squeeze(self):
        a = np.random.randn(6, 4).astype(np.float32)
        parts = ops.split(paddle.to_tensor(a), 3, axis=0)
        assert len(parts) == 3
        np.testing.assert_allclose(parts[1].numpy(), a[2:4])
        parts = ops.split(paddle.to_tensor(a), [2, -1], axis=0)
        np.testing.assert_allclose(parts[1].numpy(), a[2:])
        b = np.random.randn(1, 3, 1).astype(np.float32)
        check_output(ops.squeeze, [b], b.squeeze())
        check_output(ops.unsqueeze, [b.squeeze()], b.squeeze()[None],
                     attrs={"axis": 0})

    def test_gather_scatter(self):
        a = np.random.randn(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4], np.int64)
        check_output(ops.gather, [a, idx], a[idx])
        check_grad(ops.gather, [a, idx], grad_inputs=[0])
        upd = np.ones((2, 3), np.float32)
        out = ops.scatter(paddle.to_tensor(a),
                          paddle.to_tensor(np.array([1, 3])),
                          paddle.to_tensor(upd))
        exp = a.copy()
        exp[[1, 3]] = 1
        np.testing.assert_allclose(out.numpy(), exp)

    def test_tile_expand(self):
        a = np.random.randn(1, 3).astype(np.float32)
        check_output(ops.tile, [a], np.tile(a, (2, 2)),
                     attrs={"repeat_times": [2, 2]})
        check_output(ops.expand, [a], np.broadcast_to(a, (4, 3)),
                     attrs={"shape": [4, 3]})
        check_grad(ops.expand, [a], attrs={"shape": [4, 3]})

    def test_sort_topk(self):
        a = np.random.randn(4, 6).astype(np.float32)
        check_output(ops.sort, [a], np.sort(a, -1))
        vals, idx = ops.topk(paddle.to_tensor(a), 3)
        np.testing.assert_allclose(vals.numpy(),
                                   -np.sort(-a, -1)[:, :3], rtol=1e-6)

    def test_pad(self):
        a = np.random.randn(2, 3, 4, 5).astype(np.float32)
        out = ops.pad(paddle.to_tensor(a), [1, 1, 2, 2])
        assert out.shape == [2, 3, 6, 9]

    def test_flip_roll(self):
        a = np.random.randn(3, 4).astype(np.float32)
        check_output(ops.flip, [a], a[::-1], attrs={"axis": [0]})
        check_output(ops.roll, [a], np.roll(a, 1, 0),
                     attrs={"shifts": 1, "axis": 0})

    def test_one_hot(self):
        x = np.array([0, 2, 1], np.int64)
        check_output(ops.one_hot, [x], np.eye(3, dtype=np.float32)[x],
                     attrs={"num_classes": 3})


class TestLinalg:
    def test_matmul(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        check_output(ops.matmul, [a, b], a @ b, rtol=1e-4)
        check_grad(ops.matmul, [a, b])

    def test_matmul_transpose(self):
        a = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(5, 4).astype(np.float32)
        check_output(ops.matmul, [a, b], a.T @ b.T,
                     attrs={"transpose_x": True, "transpose_y": True},
                     rtol=1e-4)

    def test_batched_matmul(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        check_output(ops.bmm, [a, b], a @ b, rtol=1e-4)

    def test_einsum(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        out = ops.einsum("ij,jk->ik", paddle.to_tensor(a),
                         paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4)

    def test_solve_inverse(self):
        a = (np.random.randn(3, 3) + 3 * np.eye(3)).astype(np.float32)
        b = np.random.randn(3, 2).astype(np.float32)
        check_output(ops.solve, [a, b], np.linalg.solve(a, b), rtol=1e-3)
        check_output(ops.inverse, [a], np.linalg.inv(a), rtol=1e-3)

    def test_norm(self):
        a = np.random.randn(3, 4).astype(np.float32)
        check_output(ops.norm, [a], np.sqrt((a ** 2).sum()), rtol=1e-4)

    def test_svd_qr(self):
        a = np.random.randn(4, 3).astype(np.float32)
        u, s, vt = ops.svd(paddle.to_tensor(a))
        rec = u.numpy() @ np.diag(s.numpy()) @ vt.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-4)
        q, r = ops.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)

    def test_cholesky(self):
        a = np.random.randn(3, 3).astype(np.float32)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        L = ops.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd, rtol=1e-3,
                                   atol=1e-4)


class TestActivation:
    @pytest.mark.parametrize("name,ref", [
        ("relu", lambda x: np.maximum(x, 0)),
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("softplus", lambda x: np.log1p(np.exp(x))),
        ("silu", lambda x: x / (1 + np.exp(-x))),
    ])
    def test_forward(self, name, ref):
        a = np.random.randn(4, 5).astype(np.float32)
        api = getattr(ops, name)
        check_output(api, [a], ref(a), rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("name", ["relu", "sigmoid", "tanh", "gelu",
                                      "silu", "softplus", "elu"])
    def test_grad(self, name):
        a = (np.random.randn(3, 4) + 0.1).astype(np.float32)
        check_grad(getattr(ops, name), [a])

    def test_softmax(self):
        a = np.random.randn(3, 5).astype(np.float32)
        e = np.exp(a - a.max(-1, keepdims=True))
        check_output(ops.softmax, [a], e / e.sum(-1, keepdims=True),
                     rtol=1e-5)
        check_grad(ops.softmax, [a])


class TestRandomOps:
    def test_shapes_and_ranges(self):
        u = paddle.uniform([100], min=0.0, max=1.0)
        assert u.shape == [100]
        assert float(u.min()._value) >= 0.0
        assert float(u.max()._value) <= 1.0
        r = paddle.randint(0, 5, [50])
        assert int(r.max()._value) < 5
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))

    def test_dropout_train_eval(self):
        from paddle_tpu.nn import functional as F
        x = paddle.ones([1000])
        y = F.dropout(x, p=0.5, training=True)
        keep_frac = (y.numpy() != 0).mean()
        assert 0.35 < keep_frac < 0.65
        np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0)
        y_eval = F.dropout(x, p=0.5, training=False)
        np.testing.assert_allclose(y_eval.numpy(), x.numpy())
