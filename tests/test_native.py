"""Native runtime component tests (TCPStore, BatchLoader)."""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.store import TCPStore, _PyClient, _PyServer
from paddle_tpu.io.native_loader import NativeBatchAssembler
from paddle_tpu.utils import native


def test_native_lib_builds():
    assert native.available(), "csrc native library failed to build/load"


class TestTCPStore:
    def test_set_get_add(self, free_port):
        store = TCPStore("127.0.0.1", free_port, is_master=True)
        client = TCPStore("127.0.0.1", free_port, is_master=False)
        store.set("k", b"hello")
        assert client.get("k") == b"hello"
        assert client.add("ctr", 5) == 5
        assert store.add("ctr", 2) == 7
        client.delete_key("k")
        assert client.get("k") == b""
        store.close()
        client.close()

    def test_wait_blocks_until_set(self, free_port):
        store = TCPStore("127.0.0.1", free_port, is_master=True)
        results = []

        def waiter():
            c = TCPStore("127.0.0.1", free_port, is_master=False)
            results.append(c.wait("late_key"))
            c.close()

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.3)
        assert not results
        store.set("late_key", b"now")
        t.join(timeout=10)
        assert results == [b"now"]
        store.close()

    def test_barrier(self, free_port):
        store = TCPStore("127.0.0.1", free_port, is_master=True)
        n = 4
        done = []

        def rank(i):
            c = TCPStore("127.0.0.1", free_port, is_master=False)
            c.barrier("b1", n)
            done.append(i)
            c.close()

        ts = [threading.Thread(target=rank, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=15)
        assert sorted(done) == list(range(n))
        store.close()

    def test_python_fallback_protocol_interop(self, free_port):
        # python server + python client speak the same protocol as C
        srv = _PyServer(free_port)
        c = _PyClient("127.0.0.1", free_port)
        assert c._roundtrip(0, b"x", b"v") == b""
        assert c._roundtrip(1, b"x", b"") == b"v"
        import struct
        out = c._roundtrip(2, b"n", struct.pack("<q", 3))
        assert struct.unpack("<q", out)[0] == 3
        c.close()
        srv.stop()


class TestBatchLoader:
    def test_gathers_rows(self):
        data = np.arange(100 * 8, dtype=np.float32).reshape(100, 8)
        bl = NativeBatchAssembler(data, n_threads=2)
        assert bl.native
        bl.submit([3, 1, 4])
        bl.submit([10, 20])
        b1 = bl.next(3)
        b2 = bl.next(2)
        np.testing.assert_array_equal(b1, data[[3, 1, 4]])
        np.testing.assert_array_equal(b2, data[[10, 20]])
        bl.close()

    def test_many_batches_in_order(self):
        data = np.random.randn(1000, 16).astype(np.float32)
        bl = NativeBatchAssembler(data, n_threads=4)
        rng = np.random.default_rng(0)
        all_idx = [rng.integers(0, 1000, 32) for _ in range(50)]
        for idx in all_idx:
            bl.submit(idx)
        for idx in all_idx:
            out = bl.next(32)
            np.testing.assert_array_equal(out, data[idx])
        bl.close()
