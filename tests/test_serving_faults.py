"""Fault-tolerant serving cluster (PR 7).

Plan level: FaultEvent/FaultPlan validation, JSONL round-trip, seeded
synthesis determinism. Sim level: the unified token rule is
RESUME-CONSISTENT (prefilling prompt+emitted equals decoding onward —
the property failover retries stand on). Engine level: abort/crash
teardown frees slots and pages with the census balanced, the pool
purge drops every prefix key and bumps the epoch, a second session on
the same engine starts clean, a DecodeError raised inside a decode
turn tears down exactly one row. Cluster level: crash -> heartbeat
detection -> failover with exactly-once accounting and token parity
vs the fault-free replay, stalls are slow-not-dead, retry budgets
exhaust into FAILED (never lost), backoff delays re-placement,
cancel_after across a crash window counts once as cancelled, and the
serving_chaos bench-gate family (pass + graceful FAIL rows). Satellites:
truncated-tail JSONL loaders, atomic save_log, trace_report failover
hops. One real-model test proves prefill/decode resume consistency on
actual weights.
"""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import (ClusterRouter, DecodeError,
                                FailoverConfig, FaultEvent, FaultPlan,
                                QoSScheduler, Request, ServingEngine,
                                load_engine_log, load_trace,
                                make_sim_serving, save_trace,
                                synthesize_fault_plan,
                                synthesize_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COSTS = {"prefill_unit": 1.0, "decode": 1.0}


def _sim(slots=4, extra=8, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("vocab", 211)
    kw.setdefault("n_pool_pages",
                  slots * (kw["max_len"] // kw["page_size"]) + 1 + extra)
    return make_sim_serving(slots=slots, **kw)


def _engine(slots=4, scheduler=None, serving=None, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", COSTS)
    return ServingEngine(serving=serving or _sim(slots=slots),
                         slots=slots, policy="paged",
                         scheduler=scheduler, **kw)


def _req(rid, arrival, prompt, budget, **kw):
    return Request(rid=rid, arrival=arrival, prompt=tuple(prompt),
                   max_new_tokens=budget, **kw)


def _trace(n=24, seed=3, gap=0.7, plen=10, budget=8, **kw):
    rng = np.random.default_rng(seed)
    return [_req(f"m{i}", i * gap,
                 [int(t) for t in rng.integers(1, 211, plen)],
                 budget, **kw) for i in range(n)]


def _cluster(trace, n=2, faults=None, failover=None, scheduler=None,
             placement="round_robin", trace_out=None, slots=4,
             events=()):
    def spawn(name):
        return _engine(slots=slots,
                       scheduler=(QoSScheduler(max_queue=scheduler)
                                  if scheduler else None))
    if faults is not None and failover is None:
        failover = FailoverConfig(heartbeat_interval=1.0,
                                  heartbeat_timeout=3.0,
                                  backoff_base=0.5)
    return ClusterRouter(spawn, n, placement=placement, faults=faults,
                         failover=failover, trace=trace_out).run(
                             trace, events=events)


# --- fault plans ------------------------------------------------------------

def test_fault_event_validation():
    with pytest.raises(ValueError, match="kind"):
        FaultEvent(t=1.0, kind="explode", replica="r0")
    with pytest.raises(ValueError, match="duration"):
        FaultEvent(t=1.0, kind="stall", replica="r0")
    with pytest.raises(ValueError, match="no duration"):
        FaultEvent(t=1.0, kind="crash", replica="r0", duration=2.0)
    with pytest.raises(ValueError, match="dead replica"):
        FaultPlan([FaultEvent(t=1.0, kind="crash", replica="r0"),
                   FaultEvent(t=2.0, kind="stall", replica="r0",
                              duration=1.0)])


def test_fault_plan_roundtrip_and_synthesis(tmp_path):
    plan = synthesize_fault_plan(seed=4, replicas=["r0", "r1", "r2",
                                                   "r3"],
                                 span=100.0)
    assert len(plan.crashes()) == 1
    assert all(e.t <= 100.0 for e in plan)
    # crashes land mid-trace; other faults only target survivors
    victim = plan.crashes()[0].replica
    assert 35.0 <= plan.crashes()[0].t <= 65.0
    assert all(e.replica != victim for e in plan
               if e.kind != "crash")
    # same seed -> same plan; JSONL round-trips exactly
    again = synthesize_fault_plan(seed=4,
                                  replicas=["r0", "r1", "r2", "r3"],
                                  span=100.0)
    assert [e.to_json() for e in plan] == [e.to_json() for e in again]
    p = str(tmp_path / "plan.jsonl")
    plan.save(p)
    assert [e.to_json() for e in FaultPlan.load(p)] == \
        [e.to_json() for e in plan]
    with pytest.raises(ValueError, match="survive"):
        synthesize_fault_plan(seed=0, replicas=["r0"], span=10.0)


# --- sim resume consistency -------------------------------------------------

def test_sim_is_resume_consistent():
    """The fault-tolerance keystone: prefilling prompt + the first e
    emitted tokens yields exactly the stream an uninterrupted decode
    would have continued with — at the oracle AND through the engine."""
    sim = _sim()
    prompt = tuple(range(1, 11))
    full = sim.expected_stream(prompt, 10)
    for e in (1, 3, 7):
        resumed = sim.expected_stream(tuple(prompt) + tuple(full[:e]),
                                      10 - e)
        assert resumed == full[e:], e
    # engine path: a fresh engine serving the resumed request agrees
    res = _engine().run([_req("a", 0.0, prompt, 10)])
    assert res.outputs["a"] == full
    res2 = _engine().run([_req("a.retry", 0.0,
                               tuple(prompt) + tuple(full[:4]), 6)])
    assert res2.outputs["a.retry"] == full[4:]


# --- engine teardown --------------------------------------------------------

def _session_with_active(n=3):
    eng = _engine()
    s = eng.session(expect_churn=False)
    for r in _trace(n=n, gap=0.0):
        s.advance_until(r.arrival)
        s.submit(r)
    s.advance_until(6.0)  # admit + a few decode turns
    assert s.active
    return eng, s


def test_abort_row_frees_slot_and_pages():
    eng, s = _session_with_active()
    rid = sorted(s.active)[0]
    slots_before = list(s.free_slots)
    req, out = s.abort_row(rid, reason="decode_error")
    assert req.rid == rid and len(out) >= 1
    assert rid not in s.active
    assert len(s.free_slots) == len(slots_before) + 1
    assert s.book.census_ok()
    # the record MOVED: no output, no metrics row, an "abort" slot event
    assert rid not in s.outputs
    assert rid not in [v["rid"] for v in s.m.request_rows()]
    assert any(ev == "abort" and r == rid
               for _, ev, r, _ in s.slot_log)
    # survivors stream on to their full budgets
    res = s.finish()
    ref = _sim()
    for other in res.outputs:
        assert res.outputs[other] == ref.expected_stream(
            next(r.prompt for r in _trace() if r.rid == other),
            len(res.outputs[other]))


def test_crash_purges_pool_and_second_session_starts_clean():
    eng, s = _session_with_active()
    prompts = {rid: s.active[rid].req.prompt for rid in s.active}
    epoch0 = s.book.epoch
    s.crash()
    assert s.crashed
    assert [r.rid for r, _ in s.crash_salvage] == sorted(
        prompts, key=lambda r: r)  # admit order == arrival order here
    # pool GONE: zero resident, zero evictable, no key survives, epoch
    # bumped — a restarted replica can never serve pre-crash pages
    cs = s.book.cache_stats()
    assert cs["resident_pages"] == 0 and cs["evictable_pages"] == 0
    assert cs["free_pages"] == cs["n_pages"]
    assert s.book.epoch == epoch0 + 1
    for p in prompts.values():
        assert s.book.match_prefix(list(p)) == 0
    assert s.book.census_ok()
    with pytest.raises(RuntimeError, match="already crashed"):
        s.crash()
    # crashed session: clock advances, nothing processes
    s.advance_until(50.0)
    assert not s.active and s.clock.now() == 50.0
    res = s.finish()
    assert res.cache_stats["invariant_ok"]
    # a SECOND session on the same engine starts clean and serves
    s2 = eng.session()
    r = _req("fresh", 0.0, range(1, 11), 4)
    s2.submit(r)
    out = s2.finish().outputs["fresh"]
    assert out == _sim().expected_stream(r.prompt, 4)


def test_decode_error_inside_turn_kills_one_row_only():
    eng, s = _session_with_active(n=3)
    victim = sorted(s.active)[0]
    fired = []

    def hook(sess):
        if victim in sess.active and not fired:
            fired.append(True)
            raise DecodeError(victim)

    s.decode_fault_hook = hook
    res = s.finish()
    assert fired
    assert len(s.aborted) == 1
    req, out = s.aborted[0]
    assert req.rid == victim
    assert victim not in res.outputs
    ref = _sim()
    for rid in res.outputs:  # survivors: full, correct streams
        r0 = next(r for r in _trace() if r.rid == rid)
        assert res.outputs[rid] == ref.expected_stream(
            r0.prompt, r0.max_new_tokens)
    # a DecodeError for an unknown row is NOT swallowed
    eng2, s2 = _session_with_active(n=1)
    s2.decode_fault_hook = lambda sess: (_ for _ in ()).throw(
        DecodeError("nobody"))
    with pytest.raises(DecodeError):
        s2.finish()


# --- cluster failover -------------------------------------------------------

def test_fault_targeting_never_joined_replica_refuses_loudly():
    trace = _trace(n=4)
    plan = FaultPlan([FaultEvent(t=1.0, kind="crash", replica="r9")])
    with pytest.raises(ValueError, match="has not joined"):
        _cluster(trace, n=2, faults=plan)


def test_crash_failover_exactly_once_with_token_parity():
    trace = _trace(n=24)
    base = _cluster(trace, n=2).outputs()
    plan = FaultPlan([FaultEvent(t=4.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan)
    cen = res.census()
    assert cen["conserved"], cen
    assert cen["lost"] == [] and cen["duplicated"] == []
    assert cen["retried"] >= 1 and cen["failed"] == 0
    # every completed stream token-identical to the fault-free replay,
    # salvage included (the resumed rows' streams are stitched)
    out = res.outputs()
    assert set(out) == set(base)
    assert out == base
    assert res.salvaged  # some rows really were resumed mid-stream
    ev = {e["event"]: e for e in res.events}
    assert ev["crash"]["replica"] == "r0"
    assert ev["dead"]["missed_heartbeats"] >= 3
    assert ev["remove"]["census_ok"] is True
    assert ev["remove"]["resident_pages"] == 0
    # the ledger shows the hop
    moved = [rid for rid, led in res.ledger.items()
             if led["retries"]]
    assert moved and all(
        res.ledger[rid]["path"][-1] == "r1" for rid in moved
        if rid in out)
    # detection waited for the heartbeat timeout, retries for backoff
    assert ev["dead"]["t"] >= 4.0 + 3.0 - 1e-9
    # fault-free results carry NO chaos keys (byte-identity guard)
    ff = _cluster(trace, n=2)
    assert "crashes" not in ff.report()
    assert "retried" not in ff.census()


def test_requests_placed_on_undetected_dead_replica_are_rescued():
    # arrivals keep landing on r0 between its crash and detection —
    # they must fail over with the queue, counted once
    trace = _trace(n=16, gap=0.25)
    plan = FaultPlan([FaultEvent(t=1.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan)
    cen = res.census()
    assert cen["conserved"] and not cen["lost"]
    dead = next(e for e in res.events if e["event"] == "dead")
    assert dead["requeued"]  # the silent window really queued work
    base = _cluster(trace, n=2).outputs()
    assert res.outputs() == base


def test_stall_is_slow_not_dead():
    trace = _trace(n=12)
    plan = FaultPlan([FaultEvent(t=2.0, kind="stall", replica="r0",
                                 duration=10.0)])
    res = _cluster(trace, n=2, faults=plan)
    assert not [e for e in res.events if e["event"] == "dead"]
    assert [e for e in res.events if e["event"] == "stall"]
    cen = res.census()
    assert cen["conserved"] and cen["retried"] == 0
    # the stalled replica's rows finish late but token-identical
    assert res.outputs() == _cluster(trace, n=2).outputs()
    # and the stall genuinely delayed its lane's completions
    stalled = res.results["r0"].metrics.request_rows()
    ff = _cluster(trace, n=2).results["r0"].metrics.request_rows()
    assert max(v["finish"] for v in stalled) > \
        max(v["finish"] for v in ff)


def test_crashed_session_dead_letters_instead_of_shedding():
    """A dead process runs no admission policy: submissions landing on
    a crashed QoS session during the undetected-silence window must
    dead-letter for rescue, never be shed by the corpse's queue bound
    — and a drain event whose target was already removed by failover
    noops instead of killing the replay."""
    eng = _engine(scheduler=QoSScheduler(max_queue=2))
    s = eng.session()
    s.crash()
    for i in range(6):  # 3x the queue bound
        s.submit(_req(f"d{i}", 0.0, range(1, 9), 4))
    assert not s.shed_log            # the corpse shed NOTHING
    assert s.queued() == 6
    pulled = s.pull_unadmitted(outcome="failover")
    assert [r.rid for r in pulled] == [f"d{i}" for i in range(6)]
    assert s.queued() == 0
    # cluster level: crash + later drain of the (by then removed)
    # replica — the drain noops, everything still conserved
    trace = _trace(n=12)
    plan = FaultPlan([FaultEvent(t=2.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan,
                   events=[(30.0, "drain", "r0")])
    assert "drain_noop" in [e["event"] for e in res.events]
    cen = res.census()
    assert cen["conserved"] and not cen["lost"]
    assert res.outputs() == _cluster(trace, n=2).outputs()


def test_drain_of_crashed_replica_resolves_to_failover():
    """An operator drain landing on a crashed-but-undetected replica
    cannot be graceful (the in-flight rows already died) — it must
    resolve as an immediate failover so the crash salvage is retried,
    never banked away with the corpse."""
    trace = _trace(n=12)
    plan = FaultPlan([FaultEvent(t=3.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan,
                   failover=FailoverConfig(heartbeat_interval=1.0,
                                           heartbeat_timeout=50.0,
                                           backoff_base=0.5),
                   events=[(4.0, "drain", "r0")])
    ev = [e["event"] for e in res.events]
    assert "drain_found_dead" in ev and "dead" in ev
    cen = res.census()
    assert cen["conserved"], cen
    assert cen["lost"] == [] and cen["duplicated"] == []
    assert res.salvaged  # the in-flight rows really moved
    assert res.outputs() == _cluster(trace, n=2).outputs()


def test_stall_outliving_timeline_still_delays_finish():
    """A stall that extends past the last driven timeline event must
    still be eaten by the final backlog drain — finish() may not skip
    the remaining pause."""
    eng = _engine()
    s = eng.session()
    s.submit(_req("s0", 0.0, range(1, 9), 4))
    s.stall_until = 40.0
    res = s.finish()
    row = res.metrics.request("s0")
    assert row["finish"] >= 40.0
    assert res.outputs["s0"] == _sim().expected_stream(
        tuple(range(1, 9)), 4)


def test_decode_error_event_retries_oldest_row():
    trace = _trace(n=8, gap=0.5)
    plan = FaultPlan([FaultEvent(t=3.0, kind="decode_error",
                                 replica="r0")])
    res = _cluster(trace, n=2, faults=plan)
    cen = res.census()
    assert cen["conserved"] and cen["retried"] == 1
    assert res.outputs() == _cluster(trace, n=2).outputs()
    ev = next(e for e in res.events if e["event"] == "decode_error")
    assert ev["salvaged"] >= 1
    rid = ev["rid"]
    assert res.ledger[rid]["retries"] == 1


def test_backend_decode_error_fails_over_through_router():
    """A DecodeError raised from INSIDE a decode turn (the backend-
    exception path, not a scheduled fault) must fail over through the
    router: the aborted row is collected, retried on a survivor, and
    the stream completes token-identical. Without a failover config
    the router refuses LOUDLY instead of losing the row."""
    trace = _trace(n=6, gap=0.5)
    fired = []

    def make_spawn(arm):
        def spawn(name):
            eng = _engine()
            orig = eng.session

            def session(**kw):
                s = orig(**kw)
                if name == "r0":
                    def hook(sess):
                        if not fired and sess.active:
                            fired.append(arm)
                            raise DecodeError(sorted(sess.active)[0])
                    s.decode_fault_hook = hook
                return s
            eng.session = session
            return eng
        return spawn

    res = ClusterRouter(make_spawn("a"), 2, placement="round_robin",
                        failover=FailoverConfig(
                            backoff_base=0.5)).run(trace)
    assert fired
    cen = res.census()
    assert cen["conserved"] and not cen["lost"] \
        and not cen["duplicated"]
    assert any(led["retries"] for led in res.ledger.values())
    # failover-only (no plan) runs that actually retried still carry
    # the chaos accounting blocks — `faulted` tracks engagement, not
    # just plan presence
    assert res.faulted and cen["retried"] >= 1 and cen["failed"] == 0
    assert "retried_requests" in res.report()
    assert res.outputs() == _cluster(trace, n=2).outputs()
    # no failover config -> loud refusal, never a silent loss
    fired.clear()
    with pytest.raises(RuntimeError, match="no failover config"):
        ClusterRouter(make_spawn("b"), 2,
                      placement="round_robin").run(trace)


def test_unplaceable_retry_fails_accounted_not_fatal():
    """A failed-over request that no admitting survivor can fit (the
    only replica left has a smaller max_len) must land in FAILED —
    counted once, replay intact — not raise out of run()."""
    def spawn(name):
        ml = 64 if name == "r0" else 32
        return ServingEngine(
            serving=make_sim_serving(max_len=ml, page_size=8, slots=2,
                                     vocab=211,
                                     n_pool_pages=2 * (ml // 8) + 9),
            slots=2, policy="paged", clock="fixed", fixed_costs=COSTS)

    # h0 fits only r0 (footprint 40+8+1 > 32); r0 crashes mid-stream
    trace = [_req("h0", 0.0, range(1, 36), 8),
             _req("h1", 0.2, range(1, 9), 4)]
    plan = FaultPlan([FaultEvent(t=1.0, kind="crash", replica="r0")])
    res = ClusterRouter(spawn, 2, placement="least_loaded",
                        faults=plan,
                        failover=FailoverConfig(
                            heartbeat_interval=1.0,
                            heartbeat_timeout=2.0)).run(trace)
    assert "h0" in res.failed and "fit" in res.failed["h0"]
    assert "retry_unplaceable" in [e["event"] for e in res.events]
    cen = res.census()
    assert cen["conserved"], cen
    assert cen["lost"] == [] and cen["failed"] == 1


def test_retry_routes_to_the_survivor_that_fits():
    """One small joiner must not doom a failed-over request a capable
    survivor can serve: retry placement filters to fitting replicas."""
    def spawn(name):
        ml = 32 if name == "r1" else 64
        return ServingEngine(
            serving=make_sim_serving(max_len=ml, page_size=8, slots=2,
                                     vocab=211,
                                     n_pool_pages=2 * (ml // 8) + 9),
            slots=2, policy="paged", clock="fixed", fixed_costs=COSTS)

    # big fits r0/r2 (64) but not r1 (32); r2 crashes holding it
    trace = [_req("pad0", 0.0, range(1, 9), 2),
             _req("pad1", 0.1, range(10, 18), 2),
             _req("big", 0.2, range(1, 36), 8),
             _req("pad2", 0.3, range(20, 28), 2)]
    plan = FaultPlan([FaultEvent(t=1.5, kind="crash", replica="r2")])
    res = ClusterRouter(spawn, 3, placement="round_robin",
                        faults=plan,
                        failover=FailoverConfig(
                            heartbeat_interval=1.0,
                            heartbeat_timeout=2.0)).run(trace)
    assert "big" not in res.failed
    cen = res.census()
    assert cen["conserved"] and cen["lost"] == [], cen
    assert "big" in res.outputs()
    assert res.ledger["big"]["path"][-1] == "r0"  # the fitting one


def test_retry_with_no_admitting_survivor_fails_accounted():
    """The last survivor drains inside the retry's backoff window:
    the popped retry has nowhere to go — it must be recorded FAILED,
    not crash the replay through _place."""
    trace = [_req("n0", 0.0, range(1, 17), 8),
             _req("n1", 0.1, range(20, 36), 8)]
    plan = FaultPlan([FaultEvent(t=1.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan,
                   failover=FailoverConfig(heartbeat_interval=1.0,
                                           heartbeat_timeout=2.0,
                                           backoff_base=8.0),
                   events=[(3.5, "drain", "r1")])
    cen = res.census()
    assert res.failed
    assert cen["conserved"] and cen["lost"] == [], cen
    assert "retry_unplaceable" in [e["event"] for e in res.events]


def test_retry_budget_exhausts_into_failed_not_lost():
    trace = _trace(n=10)
    plan = FaultPlan([FaultEvent(t=3.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan,
                   failover=FailoverConfig(heartbeat_interval=1.0,
                                           heartbeat_timeout=3.0,
                                           retry_budget=0))
    cen = res.census()
    assert cen["failed"] >= 1
    assert res.failed and all("budget exhausted" in v
                              for v in res.failed.values())
    assert cen["conserved"], cen  # failed is ACCOUNTED, not lost
    assert cen["lost"] == [] and cen["duplicated"] == []
    per = cen["tenants"]["_none"]
    assert per["completed"] + per["shed"] + per["failed"] \
        == per["arrived"] == 10


def test_cancel_after_across_crash_window_counts_once_as_cancel():
    # in-flight churn row: crashes after 2 tokens, cancel_after=5 —
    # the retry must cancel after 3 MORE tokens, once, reason "cancel"
    trace = [_req("c0", 0.0, range(1, 11), 9, cancel_after=5),
             _req("c1", 0.0, range(20, 30), 9),
             _req("q0", 1.5, range(40, 50), 6, cancel_after=2)]
    plan = FaultPlan([FaultEvent(t=3.0, kind="crash", replica="r0")])
    res = _cluster(trace, n=2, faults=plan)
    base = _cluster(trace, n=2)
    cen = res.census()
    assert cen["conserved"] and not cen["lost"] \
        and not cen["duplicated"]
    out, bout = res.outputs(), base.outputs()
    assert out["c0"] == bout["c0"] and len(out["c0"]) == 5
    assert out["q0"] == bout["q0"] and len(out["q0"]) == 2
    # exactly one finish record, reason "cancel", on the survivor
    rows = [dict(v, replica=name) for name, r in res.results.items()
            for v in r.metrics.request_rows() if v["rid"] == "c0"]
    assert len(rows) == 1
    assert rows[0]["finish_reason"] == "cancel"
    assert rows[0]["evicted"] is True
    assert rows[0]["replica"] == "r1"


def test_chaos_replay_is_deterministic():
    from paddle_tpu.serving import synthesize_cluster_trace
    trace = synthesize_cluster_trace(seed=9, n_requests=400,
                                     service_tokens_per_unit=8.0,
                                     vocab_size=211)
    span = trace[-1].arrival - trace[0].arrival
    plan = synthesize_fault_plan(seed=1, replicas=["r0", "r1"],
                                 span=span, n_stalls=1,
                                 n_decode_errors=1)

    def one():
        res = _cluster(trace, n=2, faults=plan, scheduler=16,
                       placement="prefix_aware")
        return (json.dumps(res.report(), sort_keys=True),
                res.outputs(), res.events, res.failed)

    assert one() == one()


# --- truncated-log loaders (satellite) --------------------------------------

def test_load_engine_log_tolerates_torn_tail(tmp_path):
    res = _engine().run(_trace(n=6))
    p = str(tmp_path / "log.jsonl")
    res.save_log(p)
    whole = load_engine_log(p)
    body = open(p).read()
    open(p, "w").write(body[:-25])  # tear the final record mid-line
    with pytest.warns(UserWarning, match="truncated"):
        torn = load_engine_log(p)
    # the valid prefix survived intact
    n_whole = len(whole["decisions"]) + len(whole["slot_log"])
    n_torn = len(torn["decisions"]) + len(torn["slot_log"])
    assert n_torn == n_whole - 1
    assert torn["decisions"] == whole["decisions"][:len(
        torn["decisions"])]
    # a MID-file tear is not a crash artifact: loud error
    lines = body.splitlines(keepends=True)
    open(p, "w").write(lines[0] + lines[1][:10] + "\n"
                       + "".join(lines[2:]))
    with pytest.raises(ValueError, match="malformed"):
        load_engine_log(p)


def test_load_trace_tolerates_torn_tail(tmp_path):
    trace = synthesize_trace(seed=0, n_requests=5, vocab_size=97)
    p = str(tmp_path / "t.jsonl")
    save_trace(p, trace)
    body = open(p).read()
    open(p, "w").write(body[:-20])
    with pytest.warns(UserWarning, match="truncated"):
        torn = load_trace(p)
    assert [r.rid for r in torn] == [r.rid for r in trace[:-1]]
    assert torn == trace[:4]
    # a file with NO valid record is the wrong file, not a torn tail
    open(p, "w").write("definitely not json\n")
    with pytest.raises(ValueError, match="no valid JSONL"):
        load_trace(p)


# --- atomic save_log (satellite) --------------------------------------------

def test_save_log_atomic_failed_write_keeps_old_log(tmp_path):
    p = str(tmp_path / "log.jsonl")
    res = _engine().run(_trace(n=4))
    res.save_log(p)
    before = open(p).read()
    bad = dataclasses.replace(res)
    bad.decisions = res.decisions + [{"t": 0.0, "oops": object()}]
    with pytest.raises(TypeError):
        bad.save_log(p)
    assert open(p).read() == before          # old log survived
    assert os.listdir(tmp_path) == ["log.jsonl"]  # no tmp litter


# --- trace_report failover evidence (satellite) -----------------------------

def test_trace_report_failover_hops_and_chaos_row(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from trace_report import (chaos_events, failover_hops,
                                  load_trace as _load, report,
                                  track_names)
    finally:
        sys.path.pop(0)
    out = str(tmp_path / "chaos.json")
    trace = _trace(n=16)
    plan = FaultPlan([FaultEvent(t=3.0, kind="crash", replica="r0")])
    _cluster(trace, n=2, faults=plan, trace_out=out)
    events = _load(out)
    tracks = track_names(events)
    hops = failover_hops(events, tracks)
    assert hops
    retried = next(iter(sorted(hops)))
    assert hops[retried]["retries"] >= 1
    assert hops[retried]["path"][-1] == "r1"
    kinds = {c["event"] for c in chaos_events(events)}
    assert {"crash", "dead", "retry"} <= kinds
    txt = report(events)
    assert "crash timeline" in txt and "retries=1" in txt
    # the --json chaos row rides before the global summary
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "trace_report.py"), out,
         "--json"], capture_output=True, text=True, timeout=60,
        cwd=REPO)
    rows = [json.loads(ln) for ln in r.stdout.splitlines()]
    chaos_rows = [x for x in rows
                  if x.get("bench") == "trace_report_chaos"]
    assert len(chaos_rows) == 1
    assert chaos_rows[0]["retried_requests"] == len(hops)
    assert rows[-1]["bench"] == "trace_report"  # global still LAST
    # a fault-free trace yields NO chaos section or row
    solo = str(tmp_path / "plain.json")
    _cluster(trace, n=2, trace_out=solo)
    sev = _load(solo)
    assert chaos_events(sev) == []
    assert "crash timeline" not in report(sev)


# --- the serving_chaos bench-gate family ------------------------------------

def _run_gate(text, tmp_path):
    env = {**os.environ,
           "BENCH_GATE_SERVING_BASELINE": str(tmp_path / "b.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "serving", "-"], input=text, capture_output=True, text=True,
        timeout=60, cwd=REPO, env=env)
    return r.returncode, [json.loads(ln) for ln in
                          r.stdout.strip().splitlines()]


def _chaos_row(arm, goodput, *, conserved=True, pools=True,
               removal=True):
    return json.dumps({
        "bench": "serving_chaos", "arm": arm,
        "goodput_tokens": goodput, "conserved": conserved,
        "pool_census_ok": pools, "removal_census_ok": removal,
        "lost": [], "duplicated": [], "device": "sim"})


def _chaos_summary(*, ratio=0.9, parity=True, compared=1000,
                   crashes=1, retried=5, lost=(), dup=(),
                   membership=True):
    return json.dumps({
        "bench": "serving_chaos_summary", "crashes": crashes,
        "stalls": 2, "decode_errors": 2, "failovers": crashes,
        "retried": retried, "failed": 0, "resumed_with_salvage": 3,
        "lost": list(lost), "duplicated": list(dup),
        "conserved": True, "membership_census_ok": membership,
        "parity_ok": parity, "parity_compared": compared,
        "resumed_truncated_unexplained": [],
        "chaos_vs_fault_free_goodput": ratio, "requests": 1000,
        "replicas": 4})


def test_bench_gate_serving_chaos_family(tmp_path):
    base = [_chaos_row("fault_free", 1000),
            _chaos_row("chaos", 900)]

    rc, recs = _run_gate("\n".join(base + [_chaos_summary()]) + "\n",
                         tmp_path)
    assert rc == 0 and recs[-1]["gate"] == "pass"
    assert recs[-1]["chaos_vs_fault_free_goodput"] == 0.9

    # a lost or duplicated request is an instant FAIL
    rc, recs = _run_gate("\n".join(base + [_chaos_summary(
        lost=["c-x1"])]) + "\n", tmp_path)
    assert rc == 1 and "lost" in json.dumps(recs[-1])
    # diverged streams are correctness, not degradation
    rc, recs = _run_gate("\n".join(base + [_chaos_summary(
        parity=False)]) + "\n", tmp_path)
    assert rc == 1 and "DIVERGED" in recs[-1]["reason"]
    # sub-floor goodput FAILs naming the floor
    rc, recs = _run_gate("\n".join(base + [_chaos_summary(
        ratio=0.7)]) + "\n", tmp_path)
    assert rc == 1 and "0.8" in json.dumps(recs[-1])
    # a chaos run that injected nothing gates nothing
    rc, recs = _run_gate("\n".join(base + [_chaos_summary(
        crashes=0)]) + "\n", tmp_path)
    assert rc == 1 and "injects nothing" in recs[-1]["reason"]
    # a resumed stream shorter than fault-free with nothing on the
    # record to explain it is a resume-budget bug
    bad = json.loads(_chaos_summary())
    bad["resumed_truncated_unexplained"] = ["c-x9"]
    rc, recs = _run_gate("\n".join(base + [json.dumps(bad)]) + "\n",
                         tmp_path)
    assert rc == 1 and "dropping tokens" in recs[-1]["reason"]
    # membership census broken at a removal
    rc, recs = _run_gate("\n".join(base + [_chaos_summary(
        membership=False)]) + "\n", tmp_path)
    assert rc == 1 and "membership" in recs[-1]["reason"]
    # missing arm / missing summary: graceful FAIL, never a traceback
    rc, recs = _run_gate(base[0] + "\n", tmp_path)
    assert rc == 1 and "BOTH" in recs[-1]["reason"]
    rc, recs = _run_gate("\n".join(base) + "\n", tmp_path)
    assert rc == 1 and "UNVERIFIED" in recs[-1]["reason"]
    # broken per-arm census
    rows = [_chaos_row("fault_free", 1000),
            _chaos_row("chaos", 900, conserved=False)]
    rc, recs = _run_gate("\n".join(rows + [_chaos_summary()]) + "\n",
                         tmp_path)
    assert rc == 1 and "census" in recs[-1]["reason"]

    # a chaos FAIL is not masked by a passing qos family: combined
    # verdict last
    qos = [json.dumps({"bench": "serving_qos", "scheduler": s,
                       "goodput_tokens_per_sec": g,
                       "slo_tight_attained": 1.0, "tight_requests": 5,
                       "deadline_hits": 5, "completed": 10, "shed": 0,
                       "arrived": 10, "device": "cpu"})
           for s, g in (("fifo", 1.0), ("qos", 1.6))]
    rc, recs = _run_gate("\n".join(qos + base + [_chaos_summary(
        ratio=0.5)]) + "\n", tmp_path)
    assert rc == 1
    assert recs[-1]["combined"] is True
    assert recs[-1]["qos_gate"] == "pass"
    assert recs[-1]["chaos_gate"] == "FAIL"


# --- the end-to-end chaos arm (small) ---------------------------------------

def test_chaos_arm_end_to_end_small(tmp_path):
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "serving_workload_bench.py"),
         "--chaos", "--cluster-requests", "2000",
         "--save-fault-plan", str(tmp_path / "plan.jsonl")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-800:]
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    summ = [r for r in rows
            if r["bench"] == "serving_chaos_summary"][-1]
    assert summ["lost"] == [] and summ["duplicated"] == []
    assert summ["parity_ok"] is True and summ["crashes"] == 1
    assert summ["conserved"] and summ["membership_census_ok"]
    # the saved plan replays to the identical verdict
    again = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "serving_workload_bench.py"),
         "--chaos", "--cluster-requests", "2000",
         "--fault-plan", str(tmp_path / "plan.jsonl")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    rows2 = [json.loads(ln) for ln in again.stdout.splitlines()
             if ln.startswith("{")]
    assert rows2[-1] == summ


# --- real-model resume consistency ------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


def test_real_model_resume_from_prefix_parity(tiny_model):
    """The property the sim mimics, on actual weights: prefilling
    prompt + already-emitted tokens continues the greedy stream
    exactly where decode left it — so a failed-over request's
    resumed stream is token-identical to an uninterrupted run."""
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)

    def factory():
        return llama_serving_decode_factory(
            tiny_model, max_len=48, page_size=8, n_pool_pages=13,
            batch_capacity=2, chunked_prefill=8)

    prompt = tuple(range(3, 13))
    eng = ServingEngine(serving=factory(), slots=2, policy="paged",
                        clock="fixed", fixed_costs=COSTS)
    full = eng.run([_req("f", 0.0, prompt, 8)]).outputs["f"]
    for e in (2, 5):
        eng2 = ServingEngine(serving=factory(), slots=2,
                             policy="paged", clock="fixed",
                             fixed_costs=COSTS)
        resumed = eng2.run([_req("r", 0.0,
                                 tuple(prompt) + tuple(full[:e]),
                                 8 - e)]).outputs["r"]
        assert resumed == full[e:], e


def test_real_model_queued_cancel_across_crash(tiny_model):
    """Satellite: a churn (cancel_after) request queued at a crashed
    replica fails over and is counted ONCE, as cancelled, on the
    survivor — here on the real dense/paged routed engine, the other
    backend from the sim-paged cancel test above."""
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)

    def spawn(name):
        return ServingEngine(
            serving=llama_serving_decode_factory(
                tiny_model, max_len=48, page_size=8, n_pool_pages=13,
                batch_capacity=2, chunked_prefill=8),
            slots=2, policy="routed", clock="fixed",
            fixed_costs=COSTS)

    trace = [_req("k0", 0.0, range(3, 11), 6),
             _req("k1", 0.2, range(5, 13), 6, cancel_after=2),
             _req("k2", 0.4, range(7, 15), 4)]
    plan = FaultPlan([FaultEvent(t=0.1, kind="crash", replica="r0")])
    res = ClusterRouter(spawn, 2, placement="round_robin",
                        faults=plan,
                        failover=FailoverConfig(
                            heartbeat_interval=1.0,
                            heartbeat_timeout=2.0)).run(trace)
    cen = res.census()
    assert cen["conserved"] and not cen["lost"] \
        and not cen["duplicated"]
    assert len(res.outputs()["k1"]) == 2
    rows = [v for _, r in res.results.items()
            for v in r.metrics.request_rows() if v["rid"] == "k1"]
    assert len(rows) == 1 and rows[0]["finish_reason"] == "cancel"
    # parity with an undisturbed cluster
    assert res.outputs() == ClusterRouter(
        spawn, 2, placement="round_robin").run(trace).outputs()
