"""Runtime reshard (VERDICT r3 item 7a): live-array layout moves.

~ auto_parallel/reshard.py:603 Resharder — here GSPMD emits the
collectives. Single-process cases run on the 8-virtual-device CPU mesh;
the cross-process case spawns a 2-process jax.distributed global mesh
(the test_multihost_mesh.py pattern) and reshards a global array from
row-shard to replicated, checking every process's addressable shards.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import reshard, reshard_like


def _devs(n):
    return np.asarray(jax.devices()[:n])


def test_same_mesh_respec():
    mesh = Mesh(_devs(8), ("x",))
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    a = jax.device_put(x, NamedSharding(mesh, P("x", None)))
    b = reshard(a, mesh, P(None, "x"))
    assert b.sharding.is_equivalent_to(
        NamedSharding(mesh, P(None, "x")), b.ndim)
    np.testing.assert_array_equal(np.asarray(b), np.asarray(x))


def test_cross_mesh_move():
    m1 = Mesh(_devs(8), ("x",))
    m2 = Mesh(_devs(8).reshape(2, 4), ("a", "b"))
    x = jnp.arange(8 * 12, dtype=jnp.float32).reshape(8, 12)
    a = jax.device_put(x, NamedSharding(m1, P("x", None)))
    b = reshard(a, m2, P("a", "b"))
    assert b.sharding.mesh.axis_names == ("a", "b")
    np.testing.assert_array_equal(np.asarray(b), np.asarray(x))
    # shard shape: (8/2, 12/4)
    assert b.addressable_shards[0].data.shape == (4, 3)


def test_reshard_tensor_wrapper_and_noop():
    mesh = Mesh(_devs(4), ("x",))
    t = paddle.to_tensor(
        np.arange(16, dtype=np.float32).reshape(4, 4))
    out = reshard(t, mesh, P("x", None))
    assert hasattr(out, "_value")
    want = NamedSharding(mesh, P("x", None))
    assert out._value.sharding.is_equivalent_to(want, 2)
    # already-there fast path returns the same object
    again = reshard(out, mesh, P("x", None))
    assert again is out


def test_reshard_like():
    mesh = Mesh(_devs(8), ("x",))
    ref = jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh, P("x")))
    x = jnp.ones((8, 4))
    out = reshard_like(x, ref)
    assert out.sharding.is_equivalent_to(ref.sharding, 2)


def test_reshard_under_jit_is_constraint():
    mesh = Mesh(_devs(8), ("x",))

    @jax.jit
    def f(a):
        with mesh:
            return reshard(a * 2, mesh, P("x", None))

    out = f(jnp.ones((8, 8)))
    np.testing.assert_array_equal(np.asarray(out), 2 * np.ones((8, 8)))


_WORKER = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
sys.path.insert(0, "/root/repo")
from paddle_tpu.distributed.reshard import reshard

devs = np.asarray(jax.devices())          # 4 per process = 8 global
mesh = Mesh(devs, ("x",))
rank = int(sys.argv[2])
# build a global row-sharded array from process-local shards
global_shape = (8, 8)
sharding = NamedSharding(mesh, P("x", None))
order = list(devs.flat)
local = [jax.device_put(
            np.full((1, 8), order.index(d), np.float32), d)
         for d in jax.local_devices()]
arr = jax.make_array_from_single_device_arrays(global_shape, sharding,
                                               local)
out = reshard(arr, mesh, P(None, "x"))    # row-shard -> col-shard
rows = {}
for s in out.addressable_shards:
    rows[str(s.index)] = np.asarray(s.data).tolist()
path = os.path.join(sys.argv[3], f"shards_{rank}.json")
with open(path, "w") as f:
    json.dump(rows, f)
"""


@pytest.mark.dist_retry(n=1)
def test_cross_process_reshard(tmp_path, free_port):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    addr = f"127.0.0.1:{free_port}"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), addr, str(r), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for r in range(2)]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, out + "\n" + err

    # expected global array: row i is full of the owning device id
    # (process 0 owns rows 0-3 = ids 0-3, process 1 rows 4-7)
    want = np.repeat(np.arange(8, dtype=np.float32)[:, None], 8, axis=1)
    cols = {}
    for r in range(2):
        rows = json.loads((tmp_path / f"shards_{r}.json").read_text())
        for idx, data in rows.items():
            # idx like "(slice(None, None, None), slice(2, 3, None))"
            start = int(idx.split("slice(")[2].split(",")[0])
            cols[start] = np.asarray(data)
    # after the reshard every shard holds ALL 8 rows of its column strip
    assert len(cols) == 8, sorted(cols)
    full = np.concatenate([cols[c] for c in sorted(cols)], axis=1)
    np.testing.assert_array_equal(full, want)
