"""Flash-engine ring attention: kernel path parity vs the dense oracle.

The shapes here pass `flash_eligible` (S_local >= 256, D in {64,128},
f32/bf16), so ring_attention routes through the Pallas flash kernels
per chunk (interpret mode on CPU) with the custom ring VJP — unlike the
small-shape ring tests, which exercise the dense fallback engine."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh


def _dense_ref(q, k, v, causal, G):
    kf = jnp.repeat(k, G, axis=1) if G > 1 else k
    vf = jnp.repeat(v, G, axis=1) if G > 1 else v
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      vf.astype(jnp.float32)).astype(q.dtype)


def _qkv(B, Hq, Hkv, S, D, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    return q, k, v


class TestRingFlashEngine:
    def _assert_flash_path(self, S, n, D):
        from paddle_tpu.ops.pallas.flash_attention import flash_eligible
        assert flash_eligible(S // n, D, jnp.float32), \
            "test shape must route through the flash engine"

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense(self, causal):
        from paddle_tpu.parallel.ring_attention import ring_attention
        B, H, S, D, n = 1, 2, 512, 64, 2
        self._assert_flash_path(S, n, D)
        q, k, v = _qkv(B, H, H, S, D)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        out = ring_attention(q, k, v, mesh, axis="sep", causal=causal)
        ref = _dense_ref(q, k, v, causal, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_forward_four_devices(self):
        from paddle_tpu.parallel.ring_attention import ring_attention
        B, H, S, D, n = 1, 1, 1024, 64, 4
        self._assert_flash_path(S, n, D)
        q, k, v = _qkv(B, H, H, S, D, seed=1)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        out = ring_attention(q, k, v, mesh, axis="sep", causal=True)
        ref = _dense_ref(q, k, v, True, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_forward(self):
        from paddle_tpu.parallel.ring_attention import ring_attention
        B, Hq, Hkv, S, D, n = 1, 4, 2, 512, 64, 2
        self._assert_flash_path(S, n, D)
        q, k, v = _qkv(B, Hq, Hkv, S, D, seed=2)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        out = ring_attention(q, k, v, mesh, axis="sep", causal=True)
        ref = _dense_ref(q, k, v, True, Hq // Hkv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_match_dense(self):
        from paddle_tpu.parallel.ring_attention import ring_attention
        B, H, S, D, n = 1, 1, 512, 64, 2
        self._assert_flash_path(S, n, D)
        q, k, v = _qkv(B, H, H, S, D, seed=3)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        g = jax.grad(lambda *a: jnp.sum(
            ring_attention(*a, mesh, axis="sep", causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(_dense_ref(*a, True, 1) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
                err_msg=f"d{name} mismatch (flash ring vs dense)")

    def test_gqa_grads_match_dense(self):
        from paddle_tpu.parallel.ring_attention import ring_attention
        B, Hq, Hkv, S, D, n = 1, 4, 2, 512, 64, 2
        self._assert_flash_path(S, n, D)
        q, k, v = _qkv(B, Hq, Hkv, S, D, seed=4)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        g = jax.grad(lambda *a: jnp.sum(
            ring_attention(*a, mesh, axis="sep", causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(
            _dense_ref(*a, True, Hq // Hkv) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
                err_msg=f"d{name} mismatch (flash ring vs dense)")


class TestUlyssesFlashEngine:
    def test_forward_matches_dense(self):
        from paddle_tpu.parallel.ulysses import ulysses_attention
        B, H, S, D, n = 1, 2, 512, 64, 2
        q, k, v = _qkv(B, H, H, S, D, seed=5)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        out = ulysses_attention(q, k, v, mesh, axis="sep", causal=True)
        ref = _dense_ref(q, k, v, True, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_grads_flow(self):
        from paddle_tpu.parallel.ulysses import ulysses_attention
        B, H, S, D, n = 1, 2, 512, 64, 2
        q, k, v = _qkv(B, H, H, S, D, seed=6)
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))
        g = jax.grad(lambda *a: jnp.sum(
            ulysses_attention(*a, mesh, axis="sep", causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(_dense_ref(*a, True, 1) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
                err_msg=f"d{name} mismatch (ulysses flash vs dense)")
