"""dy2static AST conversion tests.

~ the reference's dygraph_to_static test tree
(python/paddle/fluid/tests/unittests/dygraph_to_static/): same eager-vs-
converted parity style, plus jit-traced checks that tensor-dependent
control flow actually compiles (lax.cond / lax.while_loop in the jaxpr).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.dy2static import convert_to_static, code_of


def branchy(x):
    if x.sum() > 0:
        y = x * 2
    else:
        y = x - 1
    return y


def nested_if(x):
    if x.sum() > 0:
        if x.max() > 10:
            y = x * 100
        else:
            y = x * 2
    else:
        y = -x
    return y


def loopy(x, n):
    s = x
    i = 0
    while i < n:
        s = s + x
        i = i + 1
    return s


def for_range_loop(x):
    acc = x * 0
    for i in range(4):
        acc = acc + x * (i + 1)
    return acc


def logical_fn(x, flag):
    if flag and x.sum() > 0:
        r = x
    else:
        r = -x
    return r


def not_fn(x):
    if not (x.sum() > 0):
        r = x * 0
    else:
        r = x
    return r


def temp_in_loop(x, n):
    s = x * 0
    i = 0
    while i < n:
        t = x * 2          # pure temp, first defined inside the loop
        s = s + t
        i = i + 1
    return s


class TestConversion:
    def test_source_is_rewritten(self):
        conv = convert_to_static(branchy)
        src = code_of(conv)
        assert "convert_ifelse" in src
        assert "__true_fn" in src and "__false_fn" in src

    def test_if_eager_parity(self):
        conv = convert_to_static(branchy)
        pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
        np.testing.assert_allclose(conv(pos).numpy(), branchy(pos).numpy())
        np.testing.assert_allclose(conv(neg).numpy(), branchy(neg).numpy())

    def test_if_compiles_to_lax_cond(self):
        conv = convert_to_static(branchy)

        def fn(v):
            return conv(Tensor(v))._value
        jaxpr = str(jax.make_jaxpr(fn)(jnp.zeros(2)))
        assert "cond" in jaxpr
        jf = jax.jit(fn)
        np.testing.assert_allclose(jf(jnp.asarray([1.0, 2.0])), [2.0, 4.0])
        np.testing.assert_allclose(jf(jnp.asarray([-1.0, -2.0])),
                                   [-2.0, -3.0])

    def test_nested_if(self):
        conv = convert_to_static(nested_if)
        big = paddle.to_tensor(np.array([20.0], np.float32))
        small = paddle.to_tensor(np.array([1.0], np.float32))
        neg = paddle.to_tensor(np.array([-1.0], np.float32))
        for t in (big, small, neg):
            np.testing.assert_allclose(conv(t).numpy(),
                                       nested_if(t).numpy())
        jf = jax.jit(lambda v: conv(Tensor(v))._value)
        np.testing.assert_allclose(jf(jnp.asarray([20.0])), [2000.0])
        np.testing.assert_allclose(jf(jnp.asarray([-3.0])), [3.0])

    def test_while_tensor_bound_compiles(self):
        conv = convert_to_static(loopy)
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(conv(x, 3).numpy(), [4.0, 8.0])

        def fn(v, n):
            return conv(Tensor(v), Tensor(n))._value
        jaxpr = str(jax.make_jaxpr(fn)(jnp.zeros(2), jnp.asarray(3)))
        assert "while" in jaxpr
        np.testing.assert_allclose(
            jax.jit(fn)(jnp.asarray([1.0, 2.0]), jnp.asarray(5)),
            [6.0, 12.0])

    def test_for_range(self):
        conv = convert_to_static(for_range_loop)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [10.0])

    def test_logicals(self):
        conv = convert_to_static(logical_fn)
        src = code_of(conv)
        assert "convert_logical_and" in src
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(conv(x, True).numpy(), [1.0])
        np.testing.assert_allclose(conv(x, False).numpy(), [-1.0])
        convn = convert_to_static(not_fn)
        assert "convert_logical_not" in code_of(convn)
        np.testing.assert_allclose(convn(x).numpy(), [1.0])

    def test_temp_var_in_loop(self):
        conv = convert_to_static(temp_in_loop)
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(conv(x, 3).numpy(), [6.0])
        jf = jax.jit(lambda v, n: conv(Tensor(v), Tensor(n))._value)
        np.testing.assert_allclose(jf(jnp.asarray([1.0]), jnp.asarray(4)),
                                   [8.0])

    def test_return_canonicalized_to_ifelse(self):
        # ~ return_transformer.py: the early return folds into an explicit
        # if/else assigning one return slot, so it reaches convert_ifelse
        # (round 2 left these native; round 3 canonicalizes)
        def early(x):
            if x.sum() > 0:
                return x
            return -x
        conv = convert_to_static(early)
        x = paddle.to_tensor(np.array([-2.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [2.0])
        assert "convert_ifelse" in code_of(conv)
        # and it now compiles under a tensor-dependent predicate
        out = jax.jit(lambda v: conv(Tensor(v))._value)(
            np.array([3.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [3.0])

    def test_return_in_loop_stays_native(self):
        def f(x):
            for i in range(3):
                if i == 2:
                    return x * i
            return x
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.array([2.0], np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [4.0])


class ControlFlowNet(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.sum() > 0:
            out = h * 2
        else:
            out = h * 0.5
        return out


class TestToStaticIntegration:
    def test_layer_with_control_flow(self):
        net = ControlFlowNet()
        x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
        eager = net(x).numpy()
        st = paddle.jit.to_static(net)
        got = st.forward_static(x).numpy()
        np.testing.assert_allclose(got, eager, rtol=1e-5)

    def test_function_to_static(self):
        @paddle.jit.to_static
        def f(x):
            s = x * 0
            i = 0
            while i < 3:
                s = s + x
                i = i + 1
            if s.sum() > 100:
                s = s / 10
            return s
        x = paddle.to_tensor(np.full((2,), 100.0, np.float32))
        np.testing.assert_allclose(f(x).numpy(), [30.0, 30.0])
        x2 = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(f(x2).numpy(), [3.0, 3.0])

    def test_translator_disable(self):
        pt = paddle.jit.ProgramTranslator()
        pt.enable(False)
        try:
            @paddle.jit.to_static
            def g(x):
                return x + 1
            x = paddle.ones([2])
            np.testing.assert_allclose(g(x).numpy(), [2.0, 2.0])
        finally:
            pt.enable(True)


class TestBreakContinue:
    """Flag-rewritten break/continue (~ break_continue_transformer.py):
    the same source must run natively (python values) AND compile
    (tensor condition under jit)."""

    def test_break_leaves_induction_var_at_break_value(self):
        # regression: the for-range increment must NOT run on the
        # breaking iteration (python leaves i at its break value)
        def f(x):
            for i in range(5):
                if i == 2:
                    break
            return x * i
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(conv(x).numpy(), f(x).numpy())
        np.testing.assert_allclose(conv(x).numpy(), [2.0, 2.0])

    def test_continue_in_for_range(self):
        def f(x):
            s = x * 0
            for i in range(5):
                if i % 2 == 1:
                    continue
                s = s + x
            return s
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(conv(x).numpy(), f(x).numpy())
        np.testing.assert_allclose(conv(x).numpy(), [3.0, 3.0])

    def test_break_on_tensor_condition_compiles(self):
        def f(x):
            s = x * 0
            i = x.sum() * 0  # tensor counter -> compiled while
            while i < 10:
                s = s + x
                if s.sum() >= 6:
                    break
                i = i + 1
            return s
        conv = convert_to_static(f)

        def jitted(xv):
            return conv(Tensor(xv))._value

        x = np.full((2,), 1.0, np.float32)
        out = jax.jit(jitted)(x)
        # s grows by 2 per iter; stops once sum >= 6 -> 3 iterations
        np.testing.assert_allclose(np.asarray(out), [3.0, 3.0])
        # and natively (eager) the same trajectory
        np.testing.assert_allclose(conv(paddle.to_tensor(x)).numpy(),
                                   [3.0, 3.0])

    def test_nested_break_continue(self):
        def f(x):
            s = x * 0
            for i in range(4):
                if i == 3:
                    break
                for j in range(4):
                    if j == 0:
                        continue
                    if j == 3:
                        break
                    s = s + x
            return s
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        # i in {0,1,2}, j in {1,2}: 6 additions
        np.testing.assert_allclose(conv(x).numpy(), [6.0, 6.0])


class TestStmtConverters:
    def test_assert_native_and_traced(self):
        def f(x):
            assert x.sum() > 0, "must be positive"
            return x * 2
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(conv(x).numpy(), [2.0, 2.0])
        with pytest.raises(AssertionError, match="must be positive"):
            conv(paddle.to_tensor(np.full((2,), -1.0, np.float32)))
        # traced: compiles and passes; the failing case raises at runtime
        out = jax.jit(lambda v: conv(Tensor(v))._value)(
            np.ones((2,), np.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])

    def test_cast_and_len(self):
        def f(x):
            n = len(x)          # static leading dim
            y = float(n) + x * 0
            z = int(x.sum())    # concrete eager -> python int
            return y, z
        conv = convert_to_static(f)
        x = paddle.to_tensor(np.ones((3,), np.float32))
        y, z = conv(x)
        np.testing.assert_allclose(y.numpy(), [3.0, 3.0, 3.0])
        assert z == 3 and isinstance(z, int)

    def test_cast_under_tracing(self):
        def f(x):
            return float(x > 0) * 2.0

        conv = convert_to_static(f)

        def run(v):
            out = conv(Tensor(v))
            return out._value if isinstance(out, Tensor) else out
        got = jax.jit(run)(np.asarray(3.0, np.float32))
        assert float(got) == 2.0

    def test_print_traced_does_not_break_jit(self, capsys):
        def f(x):
            print("value:", x)
            return x + 1
        conv = convert_to_static(f)
        out = jax.jit(lambda v: conv(Tensor(v))._value)(
            np.ones((2,), np.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
        # native path still prints
        conv(paddle.to_tensor(np.zeros((1,), np.float32)))
        assert "value:" in capsys.readouterr().out
