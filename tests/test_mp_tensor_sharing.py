"""incubate.multiprocessing tensor IPC reductions.

~ reference test_paddle_multiprocessing.py: tensors crossing mp queues
travel via shared memory; values round-trip, stop_gradient survives, and
the producer cache bounds live segments.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import multiprocessing as mp

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate.multiprocessing import (LRUSharedCache,
                                                 init_reductions,
                                                 rebuild_tensor,
                                                 reduce_tensor)


def _child_double(q_in, q_out):
    # spawned child: fresh interpreter, safe to use jax; register the
    # reduction so the reply Tensor also ships via shared memory
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.incubate.multiprocessing import init_reductions
    init_reductions()
    t = q_in.get()
    q_out.put(t * 2)


class TestReduction:
    def test_reduce_rebuild_roundtrip(self):
        t = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
        t.stop_gradient = False
        fn, args = reduce_tensor(t)
        assert fn is rebuild_tensor
        back = fn(*args)
        np.testing.assert_allclose(back.numpy(), t.numpy())
        assert back.stop_gradient is False

    def test_int_dtype(self):
        t = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
        fn, args = reduce_tensor(t)
        back = fn(*args)
        assert back.numpy().dtype == np.int32
        np.testing.assert_array_equal(back.numpy(), t.numpy())

    def test_cross_process_queue(self):
        init_reductions()
        # spawn, not fork: a forked child of a jax-active parent deadlocks
        # on device access (XLA threads don't survive fork) — spawn is the
        # supported IPC contract for live tensors
        ctx = mp.get_context("spawn")
        q_in, q_out = ctx.Queue(), ctx.Queue()
        p = ctx.Process(target=_child_double, args=(q_in, q_out),
                        daemon=True)
        p.start()
        t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        q_in.put(t)
        # spawn re-imports jax+paddle_tpu from scratch; on a contended
        # 1-core host that alone can take minutes
        out = q_out.get(timeout=420)
        p.join(timeout=30)
        np.testing.assert_allclose(out.numpy(), t.numpy() * 2)

    def test_lru_cache_bounds_segments(self):
        cache = LRUSharedCache()
        cache.LIMIT = 3
        from paddle_tpu.incubate.multiprocessing import allocate_shared
        names = []
        for i in range(5):
            shm, _ = allocate_shared(np.zeros(4, np.float32))
            names.append(shm.name)
            cache.put(shm.name, shm)
        assert len(cache) == 3
        assert names[-1] in cache and names[0] not in cache
        # drain remaining
        for shm in list(cache.values()):
            shm.close()
            shm.unlink()
        cache.clear()
