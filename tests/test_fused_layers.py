"""incubate.nn fused transformer layers.

~ reference test_fused_attention_op.py / test_fused_feedforward_op.py /
test_fused_multi_transformer_op.py: fused outputs must match the unfused
composition and be trainable end-to-end. The TPU fused epilogue is the
Pallas dropout-add-layernorm kernel (differentiable custom VJP).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import (FusedFeedForward,
                                    FusedMultiHeadAttention,
                                    FusedMultiTransformer,
                                    FusedTransformerEncoderLayer)


def _x(shape, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).normal(0, 1, shape).astype(np.float32))


class TestFusedFeedForward:
    def test_parity_with_unfused(self):
        paddle.seed(0)
        ffn = FusedFeedForward(32, 64, dropout_rate=0.0)
        x = _x((2, 8, 32))
        out = ffn(x)
        ref = ffn.norm(x + ffn.linear2(ffn.activation(ffn.linear1(x))))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-4)

    def test_grads_flow_through_fused_epilogue(self):
        paddle.seed(0)
        ffn = FusedFeedForward(32, 64, dropout_rate=0.0)
        x = _x((2, 8, 32))
        (ffn(x) ** 2).mean().backward()
        for p in (ffn.norm.weight, ffn.norm.bias, ffn.linear1.weight,
                  ffn.linear2.weight):
            assert p.grad is not None
            assert np.isfinite(p.grad.numpy()).all()
        assert np.abs(ffn.norm.weight.grad.numpy()).sum() > 0

    def test_pre_ln_path(self):
        paddle.seed(0)
        ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                               normalize_before=True)
        x = _x((2, 4, 16))
        out = ffn(x)
        ref = x + ffn.linear2(ffn.activation(ffn.linear1(ffn.norm(x))))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-4)

    def test_dropout_active_in_train(self):
        paddle.seed(0)
        ffn = FusedFeedForward(16, 32, dropout_rate=0.5)
        x = _x((2, 4, 16))
        a = ffn(x).numpy()
        b = ffn(x).numpy()
        assert not np.allclose(a, b)  # stochastic in training mode
        ffn.eval()
        c = ffn(x).numpy()
        d = ffn(x).numpy()
        np.testing.assert_allclose(c, d)


class TestFusedMultiHeadAttention:
    def test_forward_and_grads(self):
        paddle.seed(0)
        attn = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                       attn_dropout_rate=0.0)
        x = _x((2, 8, 32))
        out = attn(x)
        assert out.shape == [2, 8, 32]
        (out ** 2).mean().backward()
        assert attn.ln_post.weight.grad is not None
        assert np.isfinite(attn.ln_post.weight.grad.numpy()).all()

    def test_encoder_layer_trains(self):
        paddle.seed(0)
        layer = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
        opt = paddle.optimizer.Adam(parameters=layer.parameters(),
                                    learning_rate=1e-2)
        x = _x((2, 8, 32))
        tgt = _x((2, 8, 32), seed=1)
        losses = []
        for _ in range(8):
            loss = ((layer(x) - tgt) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestFusedMultiTransformer:
    def test_incremental_decode_matches_full(self):
        paddle.seed(0)
        fmt = FusedMultiTransformer(16, 2, 32, num_layers=2)
        fmt.eval()
        T = 6
        x = _x((1, T, 16))
        full = fmt(x).numpy()
        cache = fmt.gen_cache(1, max_len=T)
        outs = []
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        for t in range(T):
            step_in = Tensor(jnp.asarray(x.numpy()[:, t:t + 1]))
            o, cache = fmt(step_in, caches=cache, time_step=t)
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(inc, full, rtol=2e-3, atol=2e-3)
