"""Fault injection: crash mid-training -> elastic relaunch -> resume.

~ the reference's failure story (SURVEY.md §5: launcher watches children,
ElasticManager relaunches, checkpoints ride fs) — which the reference
itself never tests end-to-end (its tests kill processes ad hoc). Here the
full loop runs: the trainer hard-crashes (os._exit(1)) at a chosen epoch,
the launch CLI's elastic watch relaunches the pod, and train_epoch_range
resumes from the last durable checkpoint, skipping completed epochs.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys
import textwrap

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    sys.path.insert(0, "/root/repo")
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    out_dir = os.environ["TEST_OUT_DIR"]
    crash_at = int(os.environ.get("CRASH_AT_EPOCH", "-1"))

    paddle.seed(5)
    m = nn.Linear(8, 2)
    opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                learning_rate=0.05)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))

    log_path = os.path.join(out_dir, "epochs.jsonl")
    for epoch in train_epoch_range(6, model=m, optimizer=opt):
        loss = (m(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        with open(log_path, "a") as f:
            f.write(json.dumps({"epoch": epoch, "pid": os.getpid(),
                                "loss": float(loss.numpy())}) + "\\n")
        if epoch == crash_at and not os.path.exists(
                os.path.join(out_dir, "crashed")):
            open(os.path.join(out_dir, "crashed"), "w").close()
            os._exit(1)  # hard crash: no cleanup, no final checkpoint
""")


def test_crash_relaunch_resume(tmp_path):
    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_AUTO_CHECKPOINT_DIR"] = str(tmp_path / "ckpt")
    env["PADDLE_JOB_ID"] = "fault_job"
    env["CRASH_AT_EPOCH"] = "2"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--elastic_level", "1",
         "--max_restart", "2", str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    assert "elastic restart" in proc.stderr
    lines = [json.loads(ln) for ln in
             (tmp_path / "epochs.jsonl").read_text().splitlines()]
    epochs = [ln["epoch"] for ln in lines]
    pids = {ln["pid"] for ln in lines}
    # first life ran 0,1,2 then crashed AT the yield of epoch 2 (its
    # checkpoint never landed); the relaunched life re-runs 2..5
    assert epochs == [0, 1, 2, 2, 3, 4, 5], epochs
    assert len(pids) == 2  # two distinct trainer processes
    # state carried across the crash: epoch-2 rerun starts from the
    # epoch-1 checkpoint, so its loss matches the first attempt's
    first_e2 = [ln for ln in lines if ln["epoch"] == 2][0]
    second_e2 = [ln for ln in lines if ln["epoch"] == 2][1]
    assert abs(first_e2["loss"] - second_e2["loss"]) < 1e-6
    # and training progressed monotonically after resume
    assert lines[-1]["loss"] < lines[0]["loss"]
