"""Vision model zoo completion: MobileNetV3, GoogLeNet, InceptionV3,
ResNeXt/wide/densenet/shufflenet/squeezenet variants (the reference's 13
model families, python/paddle/vision/models/)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle

M = paddle.vision.models


@pytest.mark.parametrize("factory,n_out", [
    ("mobilenet_v3_small", 10),
    ("mobilenet_v3_large", 10),
    ("shufflenet_v2_x0_25", 10),
    ("shufflenet_v2_swish", 10),
    ("squeezenet1_0", 10),
])
def test_small_variants_forward(factory, n_out):
    net = getattr(M, factory)(num_classes=n_out)
    net.eval()
    out = net(paddle.randn([1, 3, 64, 64]))
    assert out.shape == [1, n_out]
    assert np.isfinite(out.numpy()).all()


def test_resnext_groups():
    net = M.resnext50_32x4d(num_classes=10)
    # grouped bottleneck: conv2 of first block has 32 groups
    conv2 = net.layer1[0].conv2
    assert conv2.groups == 32
    net.eval()
    assert net(paddle.randn([1, 3, 64, 64])).shape == [1, 10]


def test_googlenet_aux_heads():
    net = M.googlenet(num_classes=10)
    net.train()
    out, aux2, aux1 = net(paddle.randn([1, 3, 64, 64]))
    assert out.shape == [1, 10] and aux1.shape == [1, 10] \
        and aux2.shape == [1, 10]
    net.eval()
    assert net(paddle.randn([1, 3, 64, 64])).shape == [1, 10]


def test_inception_v3():
    net = M.inception_v3(num_classes=10)
    net.eval()
    assert net(paddle.randn([1, 3, 299, 299])).shape == [1, 10]


def test_densenet_variants_exist():
    for f in ("densenet161", "densenet169", "densenet201", "densenet264"):
        assert callable(getattr(M, f))
    net = M.densenet169(num_classes=10)
    net.eval()
    assert net(paddle.randn([1, 3, 64, 64])).shape == [1, 10]


@pytest.mark.skipif(
    not os.path.exists("/root/reference"),
    reason="environment-only audit: needs the reference Paddle "
           "checkout at /root/reference, which this image does not "
           "carry (auto-revives on images that do)")
def test_zoo_covers_reference_all():
    import ast
    from pathlib import Path
    ref = Path("/root/reference/python/paddle/vision/models/__init__.py")
    tree = ast.parse(ref.read_text())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    names = ast.literal_eval(node.value)
    missing = [n for n in names if not hasattr(M, n)]
    assert not missing, f"missing model zoo entries: {missing}"
