"""Static-namespace parity: static.nn layer functions, sequence ops,
program-state io, strategies, distributed entries/datasets."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


class TestStaticNNLayers:
    def test_conv_norm_stack(self, static_mode):
        prog = static.Program()
        start = static.Program()
        with static.program_guard(prog, start):
            x = static.data("x", [4, 1, 8, 8], "float32")
            h = static.nn.conv2d(x, 4, 3, padding=1, act="relu")
            h = static.nn.batch_norm(h)
            ct = static.nn.conv2d_transpose(h, 2, filter_size=3, padding=1)
            gn = static.nn.group_norm(h, 2)
            ln = static.nn.layer_norm(h)
            inorm = static.nn.instance_norm(h)
            pr = static.nn.prelu(h, mode="channel")
            out = static.nn.fc(h, 10)
            loss = paddle.mean(out)
        exe = static.Executor()
        exe.run(start)
        fetches = exe.run(
            prog, feed={"x": np.random.rand(4, 1, 8, 8).astype("float32")},
            fetch_list=[loss, ct, gn, ln, inorm, pr])
        assert fetches[0].shape == ()
        assert fetches[1].shape == (4, 2, 8, 8)
        for f in fetches:
            assert np.isfinite(f).all()

    def test_conv3d(self, static_mode):
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [2, 1, 4, 4, 4], "float32")
            h = static.nn.conv3d(x, 3, 3, padding=1)
            h = static.nn.conv3d_transpose(h, 2, filter_size=3, padding=1)
        out = static.Executor().run(
            prog, feed={"x": np.random.rand(2, 1, 4, 4, 4).astype("f4")},
            fetch_list=[h])
        assert out[0].shape == (2, 2, 4, 4, 4)

    def test_bilinear_and_row_conv(self, static_mode):
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            a = static.data("a", [3, 5], "float32")
            b = static.data("b", [3, 4], "float32")
            btp = static.nn.bilinear_tensor_product(a, b, 6)
            seq = static.data("s", [2, 7, 5], "float32")
            rc = static.nn.row_conv(seq, 2)
        out = static.Executor().run(
            prog, feed={"a": np.random.rand(3, 5).astype("f4"),
                        "b": np.random.rand(3, 4).astype("f4"),
                        "s": np.random.rand(2, 7, 5).astype("f4")},
            fetch_list=[btp, rc])
        assert out[0].shape == (3, 6)
        assert out[1].shape == (2, 7, 5)

    def test_nce_and_crf(self, static_mode):
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [6, 8], "float32")
            lab = static.data("y", [6, 1], "int64")
            loss = static.nn.nce(x, lab, num_total_classes=20)
            emis = static.data("e", [2, 5, 4], "float32")
            path = static.nn.crf_decoding(emis)
        out = static.Executor().run(
            prog, feed={"x": np.random.rand(6, 8).astype("f4"),
                        "y": np.random.randint(0, 20, (6, 1)),
                        "e": np.random.rand(2, 5, 4).astype("f4")},
            fetch_list=[loss, path])
        assert out[0].shape == (6, 1) and (out[0] > 0).all()
        assert out[1].shape == (2, 5)
        assert (out[1] >= 0).all() and (out[1] < 4).all()


class TestSequenceOps:
    def test_pool_variants(self):
        x = paddle.to_tensor(np.random.rand(3, 5, 4).astype("f4"))
        lens = paddle.to_tensor(np.array([2, 5, 3], np.int32))
        s = static.nn.sequence_pool(x, "sum", lens)
        ref = np.stack([x.numpy()[i, :n].sum(0)
                        for i, n in enumerate([2, 5, 3])])
        np.testing.assert_allclose(s.numpy(), ref, rtol=1e-5)
        mx = static.nn.sequence_pool(x, "max", lens)
        ref = np.stack([x.numpy()[i, :n].max(0)
                        for i, n in enumerate([2, 5, 3])])
        np.testing.assert_allclose(mx.numpy(), ref, rtol=1e-5)
        first = static.nn.sequence_first_step(x)
        np.testing.assert_allclose(first.numpy(), x.numpy()[:, 0])
        last = static.nn.sequence_last_step(x, lens)
        ref = np.stack([x.numpy()[i, n - 1]
                        for i, n in enumerate([2, 5, 3])])
        np.testing.assert_allclose(last.numpy(), ref, rtol=1e-5)

    def test_softmax_reverse(self):
        x = paddle.to_tensor(np.random.rand(2, 4, 3).astype("f4"))
        lens = paddle.to_tensor(np.array([2, 4], np.int32))
        sm = static.nn.sequence_softmax(x, lens)
        got = sm.numpy()
        # masked-out steps get ~0 probability
        assert got[0, 2:].max() < 1e-6
        np.testing.assert_allclose(got[0, :2].sum(0),
                                   np.ones(3), rtol=1e-5)
        rv = static.nn.sequence_reverse(x, lens)
        np.testing.assert_allclose(rv.numpy()[0, 0], x.numpy()[0, 1])
        np.testing.assert_allclose(rv.numpy()[0, 2], x.numpy()[0, 2])
        np.testing.assert_allclose(rv.numpy()[1, 0], x.numpy()[1, 3])

    def test_pad_unpad_concat_reshape(self):
        x = paddle.to_tensor(np.random.rand(2, 3, 4).astype("f4"))
        padded, lens = static.nn.sequence_pad(x, 0.0, maxlen=5)
        assert padded.shape == [2, 5, 4]
        assert list(lens.numpy()) == [3, 3]
        trimmed = static.nn.sequence_unpad(
            padded, paddle.to_tensor(np.array([3, 2], np.int32)))
        assert trimmed.shape == [2, 3, 4]
        cc = static.nn.sequence_concat([x, x])
        assert cc.shape == [2, 6, 4]
        rs = static.nn.sequence_reshape(x, 2)
        assert rs.shape == [2, 6, 2]

    def test_enumerate_slice_scatter_expand(self):
        ids = paddle.to_tensor(np.arange(8).reshape(2, 4))
        en = static.nn.sequence_enumerate(ids, 2)
        assert en.shape == [2, 4, 2]
        np.testing.assert_array_equal(en.numpy()[0, 0], [0, 1])
        x = paddle.to_tensor(np.random.rand(2, 4, 3).astype("f4"))
        sl = static.nn.sequence_slice(
            x, paddle.to_tensor(np.array([1, 0], np.int32)),
            paddle.to_tensor(np.array([2, 3], np.int32)))
        np.testing.assert_allclose(sl.numpy()[0, 0], x.numpy()[0, 1])
        assert abs(sl.numpy()[0, 2]).max() == 0  # masked beyond length
        base = paddle.zeros([2, 6])
        upd = paddle.ones([2, 2])
        idx = paddle.to_tensor(np.array([[0, 2], [1, 3]], np.int32))
        sc = static.nn.sequence_scatter(base, idx, upd)
        assert sc.numpy()[0, 0] == 1 and sc.numpy()[1, 3] == 1
        y = paddle.zeros([2, 5, 3])
        ex = static.nn.sequence_expand(paddle.ones([2, 3]), y)
        assert ex.shape == [2, 5, 3]

    def test_sequence_conv(self):
        x = paddle.to_tensor(np.random.rand(2, 6, 4).astype("f4"))
        out = static.nn.sequence_conv(x, 8, 3)
        assert out.shape == [2, 6, 8]


class TestStaticExtras:
    def test_program_state_roundtrip(self, static_mode):
        prog = static.Program()
        start = static.Program()
        with static.program_guard(prog, start):
            x = static.data("x", [2, 4], "float32")
            out = static.nn.fc(x, 3)
        exe = static.Executor()
        exe.run(start)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "model")
            static.save(prog, path)
            st = static.load_program_state(path)
            assert len(st) == len(prog.all_parameters())
            # perturb then restore
            for p in prog.all_parameters():
                p._value = p._value * 0
            static.set_program_state(prog, st)
            for p in prog.all_parameters():
                key = p.name
                if key in st:
                    np.testing.assert_allclose(np.asarray(p._value), st[key])
            blob = static.serialize_persistables(program=prog)
            static.deserialize_persistables(prog, blob)
            f = os.path.join(d, "blob.bin")
            static.save_to_file(f, blob)
            assert static.load_from_file(f) == blob

    def test_strategies_places_ema(self):
        bs = static.BuildStrategy()
        bs.reduce_strategy = static.BuildStrategy.ReduceStrategy.Reduce
        es = static.ExecutionStrategy()
        es.num_threads = 4
        assert len(static.cpu_places(3)) == 3
        assert len(static.cuda_places()) >= 1
        w = static.WeightNormParamAttr(dim=0, name="wn")
        assert w.dim == 0

    def test_ema_apply_restore(self):
        prog = static.default_main_program()
        p = static.create_parameter([2, 2], "float32", name="ema_p")
        ema = static.ExponentialMovingAverage(0.5)
        orig = np.asarray(p._value).copy()
        ema.update()
        p._value = p._value + 100.0
        ema.update()
        with ema.apply():
            inside = np.asarray(p._value)
            assert abs(inside - orig).max() < 100
        np.testing.assert_allclose(np.asarray(p._value), orig + 100.0)

    def test_accuracy_print(self):
        logits = paddle.to_tensor(
            np.array([[9.0, 1.0], [1.0, 9.0]], np.float32))
        lab = paddle.to_tensor(np.array([[0], [1]]))
        assert float(static.accuracy(logits, lab).numpy()) == 1.0
        out = static.Print(paddle.ones([2]), message="test")
        assert out.shape == [2]

    def test_device_guard(self):
        with static.device_guard("cpu"):
            t = paddle.ones([2])
        assert t.shape == [2]


class TestDistributedEntries:
    def test_entry_attrs(self):
        import paddle_tpu.distributed as dist
        assert dist.ProbabilityEntry(0.5)._to_attr() == \
            "probability_entry:0.5"
        assert dist.CountFilterEntry(3)._to_attr() == "count_filter_entry:3"
        assert "show" in dist.ShowClickEntry("show", "click")._to_attr()
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(0)
        with pytest.raises(ValueError):
            dist.CountFilterEntry(0)

    def test_in_memory_dataset(self, tmp_path):
        import paddle_tpu.distributed as dist
        f = tmp_path / "data.txt"
        f.write_text("\n".join(f"{i} {i % 3}" for i in range(10)))
        ds = dist.InMemoryDataset()
        ds.init(batch_size=4)
        ds.set_filelist([str(f)])
        ds.set_parse_fn(lambda line: tuple(
            np.int64(v) for v in line.split()))
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 10
        ds.global_shuffle()
        batches = list(ds)
        assert len(batches) == 3
        assert batches[0][0].shape == (4,)
        ds.release_memory()
        assert ds.get_memory_data_size() == 0

    def test_queue_dataset(self, tmp_path):
        import paddle_tpu.distributed as dist
        f = tmp_path / "q.txt"
        f.write_text("\n".join(str(i) for i in range(6)))
        ds = dist.QueueDataset()
        ds.init(batch_size=2)
        ds.set_filelist([str(f)])
        ds.set_parse_fn(lambda line: np.int64(line))
        assert len(list(ds)) == 3

    def test_parallel_mode(self):
        import paddle_tpu.distributed as dist
        assert dist.ParallelMode.DATA_PARALLEL == 0
        assert dist.ParallelMode.SHARDING_PARALLEL == 3


def test_sequence_erase():
    import paddle_tpu.static.nn as snn
    x = np.array([[2, 1, 3, 1, 5, 0], [1, 1, 2, 9, 0, 0]], np.int64)
    lens = np.array([5, 4], np.int32)
    out, nl = snn.sequence_erase(paddle.to_tensor(x), [1],
                                 length=paddle.to_tensor(lens))
    o = out.numpy()
    # row 0 keeps [2,3,5] (the 1s erased, pad stays out)
    np.testing.assert_array_equal(o[0, :3], [2, 3, 5])
    assert (o[0, 3:] == 0).all()
    np.testing.assert_array_equal(o[1, :2], [2, 9])
    np.testing.assert_array_equal(nl.numpy(), [3, 2])
    # multiple tokens
    out2, nl2 = snn.sequence_erase(paddle.to_tensor(x), [1, 2],
                                   length=paddle.to_tensor(lens))
    np.testing.assert_array_equal(nl2.numpy(), [2, 1])
    np.testing.assert_array_equal(out2.numpy()[0, :2], [3, 5])


def test_sequence_topk_avg_pooling():
    import jax
    import paddle_tpu.static.nn as snn
    rng = np.random.default_rng(0)
    B, C, R, L = 2, 3, 4, 6
    x = rng.normal(0, 1, (B, C, R, L)).astype(np.float32)
    col = np.array([6, 4], np.int32)
    out = snn.sequence_topk_avg_pooling(paddle.to_tensor(x), [1, 3],
                                        col=paddle.to_tensor(col))
    o = out.numpy()
    assert o.shape == (B, R, C * 2)
    # oracle for batch 1 (4 valid cols), channel 2, row 3
    vals = np.sort(x[1, 2, 3, :4])[::-1]
    np.testing.assert_allclose(o[1, 3, 2 * 2 + 0], vals[0], rtol=1e-6)
    np.testing.assert_allclose(o[1, 3, 2 * 2 + 1], vals[:3].mean(),
                               rtol=1e-6)
    # jits (static shapes)
    f = jax.jit(lambda v: snn.sequence_topk_avg_pooling(
        paddle.Tensor(v), [2])._value)
    assert f(x).shape == (B, R, C)
