"""framework/io.py atomic save: a failed write must never clobber the
previous checkpoint (tmp-file + os.replace discipline, matching
incubate/checkpoint/auto_checkpoint.py's tmp->mv)."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle


class _Unpicklable:
    def __reduce__(self):
        raise RuntimeError("simulated mid-write failure")


def test_failed_save_preserves_old_checkpoint(tmp_path):
    """A crash while pickling the NEW state leaves the OLD file intact
    and byte-valid — no truncated file where a checkpoint used to be,
    and no tmp litter in the directory."""
    path = str(tmp_path / "model.pdparams")
    old = {"w": paddle.to_tensor(np.arange(6.0).reshape(2, 3)),
           "step": 7}
    paddle.save(old, path)
    before = open(path, "rb").read()

    bad = {"w": paddle.to_tensor(np.zeros((4, 4))),
           "boom": _Unpicklable()}
    with pytest.raises(RuntimeError, match="simulated"):
        paddle.save(bad, path)

    assert open(path, "rb").read() == before  # old bytes survive
    loaded = paddle.load(path)
    np.testing.assert_array_equal(loaded["w"].numpy(),
                                  old["w"].numpy())
    assert loaded["step"] == 7
    assert os.listdir(tmp_path) == ["model.pdparams"]  # no tmp litter


def test_failed_first_save_leaves_no_file(tmp_path):
    """When there was no previous checkpoint, a failed save leaves
    NOTHING — a partial first write must not masquerade as a file."""
    path = str(tmp_path / "fresh.pdparams")
    with pytest.raises(RuntimeError, match="simulated"):
        paddle.save({"boom": _Unpicklable()}, path)
    assert os.listdir(tmp_path) == []


def test_save_still_round_trips(tmp_path):
    """The happy path through the tmp+replace discipline is unchanged:
    nested state dicts round-trip, and the on-disk file is one valid
    pickle (no tmp suffix leaked into the final name)."""
    path = str(tmp_path / "nested" / "opt.pdopt")  # dir auto-created
    state = {"lr": 0.1,
             "moments": [paddle.to_tensor(np.ones((3,)))],
             "name": "adam"}
    paddle.save(state, path)
    assert sorted(os.listdir(tmp_path / "nested")) == ["opt.pdopt"]
    with open(path, "rb") as f:
        pickle.load(f)  # one complete pickle stream
    back = paddle.load(path)
    assert back["lr"] == 0.1 and back["name"] == "adam"
    np.testing.assert_array_equal(back["moments"][0].numpy(),
                                  np.ones((3,)))
