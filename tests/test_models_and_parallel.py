"""Model zoo + compiled-parallelism tests over the 8-device CPU mesh.

Mirrors the reference's distributed parity strategy (SURVEY.md §4): the
multi-device result must match the single-device oracle.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.models.nlp import (BertConfig, BertForPretraining, GPTConfig,
                                   GPTForCausalLM, LlamaConfig,
                                   LlamaForCausalLM, MoEConfig,
                                   MoEForCausalLM)
from paddle_tpu.models.nlp.llama import llama_train_step_factory


def _tokens(B, S, V, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, V, (B, S)).astype(np.int32)


class TestModels:
    def test_llama_forward_and_backward(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(_tokens(2, 16, cfg.vocab_size))
        logits = model(ids)
        assert logits.shape == [2, 16, cfg.vocab_size]
        from paddle_tpu.nn import functional as F
        labels = paddle.to_tensor(_tokens(2, 16, cfg.vocab_size, 1).astype(np.int64))
        loss = F.cross_entropy(logits, labels)
        loss.backward()
        g = model.model.layers[0].self_attn.q_proj.weight.grad
        assert g is not None and float(np.abs(g.numpy()).max()) > 0

    def test_llama_generate(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        model.eval()
        ids = paddle.to_tensor(_tokens(1, 4, cfg.vocab_size))
        out = model.generate(ids, max_new_tokens=3)
        assert out.shape == [1, 7]

    def test_gpt_forward(self):
        cfg = GPTConfig.tiny()
        model = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(_tokens(2, 8, cfg.vocab_size))
        logits = model(ids)
        assert logits.shape == [2, 8, cfg.vocab_size]

    def test_bert_pretraining(self):
        cfg = BertConfig.tiny()
        model = BertForPretraining(cfg)
        ids = paddle.to_tensor(_tokens(2, 12, cfg.vocab_size))
        mask = paddle.ones([2, 12], dtype="float32")
        mlm, nsp = model(ids, attention_mask=mask)
        assert mlm.shape == [2, 12, cfg.vocab_size]
        assert nsp.shape == [2, 2]
        mlm_labels = paddle.to_tensor(
            _tokens(2, 12, cfg.vocab_size, 3).astype(np.int64))
        nsp_labels = paddle.to_tensor(np.array([0, 1], np.int64))
        loss = model.loss(mlm, nsp, mlm_labels, nsp_labels)
        loss.backward()
        assert model.bert.embeddings.word_embeddings.weight.grad is not None

    def test_moe_forward_backward(self):
        cfg = MoEConfig.tiny()
        model = MoEForCausalLM(cfg)
        ids = paddle.to_tensor(_tokens(2, 8, cfg.vocab_size))
        logits = model(ids)
        assert logits.shape == [2, 8, cfg.vocab_size]
        from paddle_tpu.nn import functional as F
        labels = paddle.to_tensor(_tokens(2, 8, cfg.vocab_size, 1).astype(np.int64))
        loss = F.cross_entropy(logits, labels) + model.aux_loss()
        loss.backward()
        moe_layer = model.layers[0].mlp
        assert moe_layer.w_in.grad is not None
        assert float(np.abs(moe_layer.w_in.grad.numpy()).sum()) > 0

    def test_moe_capacity_dispatch_sums(self):
        from paddle_tpu.incubate.distributed.models.moe import top1_gating
        logits = jnp.asarray(np.random.randn(32, 4).astype(np.float32))
        dispatch, combine, aux = top1_gating(logits, capacity=16)
        # each token routed to at most one slot
        assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0 + 1e-6
        # no slot used twice
        assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
        assert float(aux) > 0


class TestFlashAttention:
    def _ref(self, q, k, v, causal):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            S = q.shape[2]
            s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -1e30)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 2, 256, 64), np.float32))
        k = jnp.asarray(rng.standard_normal((1, 2, 256, 64), np.float32))
        v = jnp.asarray(rng.standard_normal((1, 2, 256, 64), np.float32))
        out = flash_attention(q, k, v, causal)
        ref = self._ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_backward_matches_reference(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.standard_normal((1, 1, 256, 64), np.float32))
        k = jnp.asarray(rng.standard_normal((1, 1, 256, 64), np.float32))
        v = jnp.asarray(rng.standard_normal((1, 1, 256, 64), np.float32))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(self._ref(q, k, v, True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-3)


class TestFusedNorms:
    def test_layer_norm_kernel(self):
        from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm
        x = jnp.asarray(np.random.randn(64, 128).astype(np.float32))
        w = jnp.asarray(np.random.randn(128).astype(np.float32))
        b = jnp.asarray(np.random.randn(128).astype(np.float32))
        out = fused_layer_norm(x, w, b)
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        ref = (x - mu) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_rms_norm_kernel(self):
        from paddle_tpu.ops.pallas.layer_norm import fused_rms_norm
        x = jnp.asarray(np.random.randn(32, 256).astype(np.float32))
        w = jnp.asarray(np.random.randn(256).astype(np.float32))
        out = fused_rms_norm(x, w)
        ref = x / jnp.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


def _mesh(shape_dict):
    devs = np.asarray(jax.devices()[:int(np.prod(list(shape_dict.values())))])
    return Mesh(devs.reshape(tuple(shape_dict.values())),
                tuple(shape_dict.keys()))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        from paddle_tpu.parallel import ring_attention
        mesh = _mesh({"sep": 4})
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((2, 2, 64, 16), np.float32))
        k = jnp.asarray(rng.standard_normal((2, 2, 64, 16), np.float32))
        v = jnp.asarray(rng.standard_normal((2, 2, 64, 16), np.float32))
        out = ring_attention(q, k, v, mesh, causal=causal)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / 4.0
        if causal:
            s = jnp.where(jnp.tril(jnp.ones((64, 64), bool)), s, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestPipeline:
    def test_pipeline_matches_sequential(self):
        from paddle_tpu.parallel import pipeline_apply, stack_stage_params
        mesh = _mesh({"pipe": 4})
        rng = np.random.default_rng(0)
        # 4 stages, each y = tanh(x @ W_s)
        Ws = [jnp.asarray(rng.standard_normal((16, 16), np.float32) * 0.3)
              for _ in range(4)]
        stacked = stack_stage_params([{"w": w} for w in Ws])

        def stage_fn(params, x):
            return jnp.tanh(x @ params["w"])

        x = jnp.asarray(rng.standard_normal((8, 16), np.float32))
        y = pipeline_apply(stage_fn, stacked, x, mesh, n_microbatches=4)
        ref = x
        for w in Ws:
            ref = jnp.tanh(ref @ w)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_pipeline_grad(self):
        from paddle_tpu.parallel import pipeline_apply, stack_stage_params
        mesh = _mesh({"pipe": 2})
        rng = np.random.default_rng(1)
        Ws = [jnp.asarray(rng.standard_normal((8, 8), np.float32) * 0.3)
              for _ in range(2)]
        stacked = stack_stage_params([{"w": w} for w in Ws])
        x = jnp.asarray(rng.standard_normal((4, 8), np.float32))

        def stage_fn(params, x):
            return jnp.tanh(x @ params["w"])

        def loss_pipe(stacked):
            return jnp.sum(pipeline_apply(stage_fn, stacked, x, mesh, 2) ** 2)

        def loss_ref(stacked):
            h = jnp.tanh(x @ stacked["w"][0])
            h = jnp.tanh(h @ stacked["w"][1])
            return jnp.sum(h ** 2)

        gp = jax.grad(loss_pipe)(stacked)["w"]
        gr = jax.grad(loss_ref)(stacked)["w"]
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)


class TestGSPMDTrainStep:
    def test_llama_dp_tp_step_runs_and_matches_single(self):
        cfg = LlamaConfig.tiny()
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        mesh = _mesh({"data": 2, "model": 4})
        params, opt_state, step, batch_sh = llama_train_step_factory(
            model, mesh, learning_rate=1e-2, remat=False)
        tokens = jnp.asarray(_tokens(4, 16, cfg.vocab_size))
        labels = jnp.asarray(_tokens(4, 16, cfg.vocab_size, 1))
        p1, o1, loss1 = step(params, opt_state, tokens, labels)
        p2, o2, loss2 = step(p1, o1, tokens, labels)
        assert np.isfinite(float(loss1))
        assert float(loss2) < float(loss1)  # same batch → loss must drop

    def test_llama_dp_tp_matches_single_device_loss(self):
        cfg = LlamaConfig.tiny()
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        tokens = jnp.asarray(_tokens(4, 16, cfg.vocab_size))
        labels = jnp.asarray(_tokens(4, 16, cfg.vocab_size, 1))

        # single-device oracle loss
        from paddle_tpu.core.tensor import Tensor
        model_params = {k: v._value for k, v in model.state_dict().items()}

        def oracle_loss(params):
            model.load_tree(params)
            logits = model(Tensor(tokens))._value.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.mean(-jnp.take_along_axis(
                logp, labels[..., None].astype(jnp.int32), -1)[..., 0])

        ref = float(jax.jit(oracle_loss)(model_params))
        model.load_tree(model_params)  # restore concrete values post-trace

        mesh = _mesh({"data": 2, "model": 4})
        params, opt_state, step, _ = llama_train_step_factory(
            model, mesh, learning_rate=1e-2, remat=False)
        _, _, loss = step(params, opt_state, tokens, labels)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


class TestContextParallelLlama:
    """Ring attention wired into the flagship when the mesh has sep>1
    (round-1 verdict #4): loss parity with the single-device oracle and a
    collective-permute (ring KV rotation) in the lowered step — not an
    all-gather of the sequence."""

    def _oracle(self, model, tokens, labels):
        from paddle_tpu.core.tensor import Tensor
        model_params = {k: v._value for k, v in model.state_dict().items()}

        def oracle_loss(params):
            model.load_tree(params)
            logits = model(Tensor(tokens))._value.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.mean(-jnp.take_along_axis(
                logp, labels[..., None].astype(jnp.int32), -1)[..., 0])

        ref = float(jax.jit(oracle_loss)(model_params))
        model.load_tree(model_params)
        return ref

    def test_sep_parity_and_ring_in_hlo(self):
        cfg = LlamaConfig.tiny()
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        tokens = jnp.asarray(_tokens(4, 32, cfg.vocab_size))
        labels = jnp.asarray(_tokens(4, 32, cfg.vocab_size, 1))
        ref = self._oracle(model, tokens, labels)

        mesh = _mesh({"data": 2, "sep": 2, "model": 2})
        params, opt_state, step, _ = llama_train_step_factory(
            model, mesh, learning_rate=1e-2, remat=False)
        lowered = step.lower(params, opt_state, tokens, labels)
        stablehlo = lowered.as_text()
        assert "collective_permute" in stablehlo, \
            "sep>1 step must rotate KV via ppermute (ring attention)"
        _, _, loss = step(params, opt_state, tokens, labels)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)

    def test_sep_only_mesh_parity(self):
        cfg = LlamaConfig.tiny()
        paddle.seed(1)
        model = LlamaForCausalLM(cfg)
        tokens = jnp.asarray(_tokens(2, 64, cfg.vocab_size))
        labels = jnp.asarray(_tokens(2, 64, cfg.vocab_size, 1))
        ref = self._oracle(model, tokens, labels)
        mesh = _mesh({"sep": 4})
        params, opt_state, step, _ = llama_train_step_factory(
            model, mesh, learning_rate=1e-2, remat=True)
        _, _, loss = step(params, opt_state, tokens, labels)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


class TestGPTFamily:
    def test_gpt_generate_and_pretrain_factory(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        import paddle_tpu as paddle
        from paddle_tpu.models.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                               gpt_pretrain_step_factory)

        paddle.seed(0)
        cfg = GPTConfig.tiny()
        m = GPTForCausalLM(cfg)
        m.eval()
        prompt = paddle.to_tensor(
            np.arange(6, dtype=np.int64).reshape(1, 6))
        out = m.generate(prompt, max_new_tokens=4)
        assert tuple(out.shape) == (1, 10)
        np.testing.assert_array_equal(out.numpy()[:, :6], prompt.numpy())

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        params, opt, step = gpt_pretrain_step_factory(m, mesh)
        rng = np.random.default_rng(0)
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                          jnp.int32)
        lab = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                          jnp.int32)
        losses = []
        for _ in range(3):
            params, opt, loss = step(params, opt, tok, lab)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


def test_deepseek_style_shared_experts():
    """DeepSeekMoE/Qwen2-MoE shape (BASELINE config 5): fine-grained
    routed experts + an always-on shared expert; training must reduce
    loss and the shared expert must actually contribute."""
    import jax.numpy as jnp
    from paddle_tpu.models.nlp import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = MoEConfig.deepseek_tiny()
    m = MoEForCausalLM(cfg)
    assert any(l.shared_mlp is not None for l in m.layers)
    tokens = paddle.to_tensor(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (2, 16)).astype(np.int32))
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=m.parameters())
    losses = []
    for _ in range(8):
        logits = m(tokens)
        loss = paddle.nn.functional.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            tokens.reshape([-1])) + m.aux_loss()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    # ablation: zeroing the shared expert's output changes the logits
    m.eval()
    base = m(tokens).numpy()
    for l in m.layers:
        if l.shared_mlp is not None:
            for p in l.shared_mlp.parameters():
                p.set_value(paddle.zeros(p.shape))
    ablated = m(tokens).numpy()
    assert np.abs(base - ablated).max() > 1e-4
