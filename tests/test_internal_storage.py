"""Fused flat buffers + bucketed DP grad sync.

~ reference group_sharded_storage.py + Reducer bucket tests: pack/unpack
round-trips, byte-budget bucketing, and fused_all_reduce preserving
order/shape across mixed dtypes.
"""
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.fleet.utils.internal_storage import (
    GradStorage, TensorBucket, fused_all_reduce)


class TestTensorBucket:
    def test_pack_unpack_roundtrip(self):
        b = TensorBucket(jnp.float32)
        xs = [jnp.arange(6.).reshape(2, 3), jnp.ones(4), jnp.zeros((1, 2))]
        for x in xs:
            b.add(x)
        flat = b.pack()
        assert flat.shape == (12,)
        out = b.unpack(flat)
        for x, o in zip(xs, out):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(o))


class TestGradStorage:
    def test_byte_budget_splits_buckets(self):
        gs = GradStorage(max_bucket_bytes=40)  # 10 f32 elements
        grads = [jnp.ones(8), jnp.ones(8), jnp.ones(2)]
        buckets = gs.build(grads)
        assert len(buckets) == 2  # 8 | 8+2
        assert buckets[0].numel == 8 and buckets[1].numel == 10

    def test_mixed_dtypes_separate_buckets(self):
        gs = GradStorage()
        buckets = gs.build([jnp.ones(3, jnp.float32),
                            jnp.ones(3, jnp.bfloat16)])
        assert len(buckets) == 2
        assert {b.dtype for b in buckets} == {jnp.dtype(jnp.float32),
                                              jnp.dtype(jnp.bfloat16)}


class TestFusedAllReduce:
    def test_preserves_order_and_values(self):
        grads = [jnp.full((2, 2), 1.0), jnp.full((3,), 2.0),
                 jnp.full((1,), 3.0, jnp.bfloat16)]
        calls = []

        def fake_allreduce(flat):
            calls.append(flat.shape[0])
            return flat * 2  # "sum over 2 ranks"

        out = fused_all_reduce(grads, fake_allreduce)
        assert len(calls) == 2  # f32 bucket + bf16 bucket, not 3 calls
        np.testing.assert_allclose(np.asarray(out[0]), 2.0)
        np.testing.assert_allclose(np.asarray(out[1]), 4.0)
        assert out[2].dtype == jnp.bfloat16
        assert out[0].shape == (2, 2) and out[1].shape == (3,)

    def test_interleaved_dtypes_restore_order(self):
        # f32 / bf16 / f32 / bf16: assignment tracking must restore the
        # exact input order across interleaved dtype buckets
        grads = [jnp.full((2,), 1.0, jnp.float32),
                 jnp.full((3,), 2.0, jnp.bfloat16),
                 jnp.full((4,), 3.0, jnp.float32),
                 jnp.full((5,), 4.0, jnp.bfloat16)]
        out = fused_all_reduce(grads, lambda f: f * 10)
        for g, o in zip(grads, out):
            assert o.dtype == g.dtype and o.shape == g.shape
            np.testing.assert_allclose(np.asarray(o, np.float32),
                                       np.asarray(g, np.float32) * 10)
