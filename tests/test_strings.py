"""StringTensor + strings kernels + FasterTokenizer.

~ reference phi strings kernels (strings_lower_upper_kernel.h) and
test_faster_tokenizer_op.py: tokenization output must match the
HuggingFace-style BERT basic+wordpiece algorithm on the same vocab.
"""
import numpy as np

from paddle_tpu.text.strings import (BasicTokenizer, FasterTokenizer,
                                     StringTensor, WordpieceTokenizer,
                                     lower, to_string_tensor, upper)


class TestStringTensor:
    def test_basic(self):
        st = to_string_tensor(["Hello", "World"])
        assert st.shape == (2,)
        assert st.tolist() == ["Hello", "World"]
        assert st[0] == "Hello"
        assert len(st) == 2

    def test_lower_upper(self):
        st = StringTensor(["HeLLo", "WöRLD", "Straße"])
        assert lower(st).tolist() == ["hello", "wörld", "straße"]
        assert upper(st).tolist() == ["HELLO", "WÖRLD", "STRASSE"]

    def test_nd_shape(self):
        st = StringTensor(np.array([["a", "B"], ["c", "D"]], object))
        assert st.shape == (2, 2)
        assert lower(st).tolist() == [["a", "b"], ["c", "d"]]


class TestBasicTokenizer:
    def test_whitespace_punct(self):
        bt = BasicTokenizer(do_lower_case=True)
        assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]

    def test_accent_stripping(self):
        bt = BasicTokenizer(do_lower_case=True)
        assert bt.tokenize("Héllo") == ["hello"]

    def test_chinese_chars_split(self):
        bt = BasicTokenizer()
        assert bt.tokenize("你好ab") == ["你", "好", "ab"]


class TestWordpiece:
    def test_greedy_longest_match(self):
        vocab = {"un": 0, "##aff": 1, "##able": 2, "[UNK]": 3, "aff": 4}
        wp = WordpieceTokenizer(vocab)
        assert wp.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert wp.tokenize("zzz") == ["[UNK]"]


class TestFasterTokenizer:
    VOCAB = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3,
             "hello": 4, "world": 5, "##s": 6, ",": 7, "!": 8, "good": 9}

    def test_single_text(self):
        tok = FasterTokenizer(self.VOCAB)
        ids, types = tok(["Hello, Worlds!"])
        # [CLS] hello , world ##s ! [SEP]
        np.testing.assert_array_equal(ids[0], [2, 4, 7, 5, 6, 8, 3])
        assert types.sum() == 0

    def test_pair_and_padding(self):
        tok = FasterTokenizer(self.VOCAB)
        ids, types = tok(["hello"], text_pair=["good world"],
                         max_seq_len=10, pad_to_max_seq_len=True)
        assert ids.shape == (1, 10)
        # [CLS] hello [SEP] good world [SEP] [PAD]...
        np.testing.assert_array_equal(ids[0, :6], [2, 4, 3, 9, 5, 3])
        np.testing.assert_array_equal(types[0, :6], [0, 0, 0, 1, 1, 1])
        np.testing.assert_array_equal(ids[0, 6:], 0)

    def test_batch_ragged_padding(self):
        tok = FasterTokenizer(self.VOCAB)
        ids, _ = tok(["hello", "hello world !"])
        assert ids.shape[0] == 2
        assert ids[0, -1] == 0  # short row padded

    def test_truncation(self):
        tok = FasterTokenizer(self.VOCAB)
        ids, _ = tok(["hello world hello world hello"], max_seq_len=5)
        assert ids.shape[1] == 5
        assert ids[0, -1] == 3  # ends with [SEP]

    def test_string_tensor_input(self):
        tok = FasterTokenizer(self.VOCAB)
        ids, _ = tok(to_string_tensor(["hello world"]))
        np.testing.assert_array_equal(ids[0], [2, 4, 5, 3])

    def test_native_python_parity(self):
        """The C fast path (csrc/wordpiece.cc) must match the Python
        pipeline exactly on the ASCII inputs it accepts, and flag
        non-ASCII rows for per-text Python fallback."""
        from paddle_tpu.utils import native as _nat
        tok = FasterTokenizer(self.VOCAB)
        ascii_texts = ["Hello, Worlds!", "good world hello",
                       "unknownword hello", "!,!", "", "   hello   "]
        fast = tok._encode_batch_native(ascii_texts)
        if _nat.get_lib() is None or not hasattr(_nat.get_lib(),
                                                 "wp_new"):
            assert all(f is None for f in fast)  # graceful degrade
            return
        for t, f in zip(ascii_texts, fast):
            assert f is not None, t
            assert f == tok._encode_one(t), t
        # unicode rows come back None and the full pipeline still works
        mixed = ["hello world", "héllo wörld"]
        fast = tok._encode_batch_native(mixed)
        assert fast[0] is not None and fast[1] is None
        ids, _ = tok(mixed)
        assert ids.shape[0] == 2  # end-to-end path healthy
