"""Multi-replica serving cluster (PR 6).

Sim level: the deterministic pool-backed token rule matches its
closed-form oracle, is independent of slot count (tokens depend only on
the request's own history), and decorrelates under ``salt``. Session
level: the incremental ``EngineSession`` reproduces ``run()`` outputs /
slot logs / sheds on both admission disciplines. Cluster level:
placement policies, drain/join edge cases (zero in-flight, requeue
under overload with no double-counting, cold-cache join), full-replay
determinism, rollup/census, the shared-helper extraction
(``jain_fairness``/``goodput_tokens``), the ``replica`` log field
round-trip, per-replica trace-report rows, and the ``serving_cluster``
bench-gate contract (no model needed for any of those). One real-model
smoke proves cluster streams equal a lone engine's on actual weights.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import (ClusterRouter, QoSScheduler, Request,
                                ServingEngine, goodput_tokens,
                                jain_fairness, load_engine_log,
                                make_placement, make_sim_serving,
                                synthesize_cluster_trace,
                                synthesize_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COSTS = {"prefill_unit": 1.0, "decode": 1.0}


def _sim(slots=4, extra=8, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("vocab", 211)
    kw.setdefault("n_pool_pages",
                  slots * (kw["max_len"] // kw["page_size"]) + 1 + extra)
    return make_sim_serving(slots=slots, **kw)


def _engine(slots=4, scheduler=None, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", COSTS)
    return ServingEngine(serving=_sim(slots=slots), slots=slots,
                         policy="paged", scheduler=scheduler, **kw)


def _req(rid, arrival, prompt, budget, **kw):
    return Request(rid=rid, arrival=arrival, prompt=tuple(prompt),
                   max_new_tokens=budget, **kw)


def _mixed_trace(n=24, seed=3, **kw):
    kw.setdefault("arrival", "poisson")
    kw.setdefault("mean_interarrival", 0.5)
    kw.setdefault("prompt_len", (4, 20))
    kw.setdefault("output_len", (3, 10))
    kw.setdefault("vocab_size", 211)
    return synthesize_trace(seed=seed, n_requests=n, rid_prefix="m",
                            **kw)


def _run_cluster(trace, n=2, placement="round_robin", scheduler=None,
                 events=(), slots=4, trace_out=None):
    def spawn(name):
        return _engine(slots=slots,
                       scheduler=(QoSScheduler(max_queue=scheduler)
                                  if scheduler else None))
    r = ClusterRouter(spawn, n, placement=placement, trace=trace_out)
    return r.run(trace, events=events)


# --- shared metric helpers (satellite) --------------------------------------

def test_jain_fairness_helper():
    assert jain_fairness([5.0, 5.0, 5.0]) == 1.0
    assert jain_fairness([9.0, 0.0, 0.0]) == pytest.approx(1 / 3,
                                                           abs=5e-4)
    assert jain_fairness([0.0, 0.0]) is None
    assert jain_fairness([]) is None
    # the qos block and the helper are ONE implementation: a lone
    # engine's QoS report must carry exactly the helper's value
    tr = [_req(f"q{i}", 0.0, range(1, 9), 4, tenant=t)
          for i, t in enumerate(["a", "a", "b"])]
    res = _engine().run(tr)
    rep = res.report()
    xs = [rep["tenants"][t]["goodput_tokens"] for t in sorted(
        rep["tenants"])]
    assert rep["fairness_jain"] == jain_fairness(xs)


def test_goodput_tokens_helper():
    views = [{"n_tokens": 5, "deadline_met": True},
             {"n_tokens": 7, "deadline_met": False},
             {"n_tokens": 2, "deadline_met": True}]
    assert goodput_tokens(views) == 7


# --- the sim backend --------------------------------------------------------

def test_sim_matches_closed_form_oracle():
    sim = _sim()
    eng = ServingEngine(serving=sim, slots=4, policy="paged",
                        clock="fixed", fixed_costs=COSTS)
    trace = _mixed_trace(shared_prefix_frac=0.4, prefix_len=8,
                         churn_frac=0.2)
    res = eng.run(trace)
    ref = _sim()  # fresh sim: expected_stream must not depend on state
    for r in trace:
        got = res.outputs[r.rid]
        assert got == ref.expected_stream(r.prompt, len(got)), r.rid
    assert res.cache_stats["invariant_ok"]


def test_sim_tokens_independent_of_slots_and_salt():
    trace = _mixed_trace(n=12)
    a = _engine(slots=2).run(trace)
    b = _engine(slots=6).run(trace)
    assert a.outputs == b.outputs  # batch shape never leaks into tokens
    salted = ServingEngine(serving=_sim(salt=1), slots=4,
                           policy="paged", clock="fixed",
                           fixed_costs=COSTS).run(trace)
    assert salted.outputs != a.outputs  # the negative control


def test_sim_is_paged_only():
    with pytest.raises(NotImplementedError, match="paged-only"):
        _sim().dense._parts["prefill"]()
    with pytest.raises(ValueError, match="multiple"):
        make_sim_serving(max_len=60, page_size=8)


# --- EngineSession vs run() -------------------------------------------------

def _drive_session(eng, trace, **kw):
    s = eng.session(**kw)
    for r in sorted(trace, key=lambda r: (r.arrival, r.rid)):
        s.advance_until(r.arrival)
        s.submit(r)
    return s.finish()


def test_session_matches_run_fifo():
    trace = _mixed_trace(shared_prefix_frac=0.4, prefix_len=8,
                         churn_frac=0.2)
    res = _engine().run(trace)
    ses = _drive_session(_engine(), trace, expect_churn=True)
    assert ses.outputs == res.outputs
    assert ses.slot_log == res.slot_log
    assert ses.decisions == res.decisions
    assert ses.prefix_cached == res.prefix_cached
    assert ses.cache_stats == res.cache_stats


def test_session_matches_run_qos():
    from paddle_tpu.serving import synthesize_overload_trace
    trace = synthesize_overload_trace(seed=0, n_requests=40,
                                      service_tokens_per_unit=4.0,
                                      overload=2.0, vocab_size=211)
    w = {"intl": 2.0, "std": 1.0, "bulk": 0.5}
    res = _engine(scheduler=QoSScheduler(tenant_weights=w)).run(trace)
    ses = _drive_session(
        _engine(scheduler=QoSScheduler(tenant_weights=w)), trace)
    assert ses.outputs == res.outputs
    assert ses.shed == res.shed
    assert ses.slot_log == res.slot_log
    a, b = res.report(tenant_weights=w), ses.report(tenant_weights=w)
    # every per-request metric agrees; the one sampled diagnostic with
    # a different cadence is queue_depth (documented on EngineSession)
    for k in a:
        if not k.startswith("queue_depth"):
            assert a[k] == b[k], k


# --- placement policies -----------------------------------------------------

def test_round_robin_rotates():
    trace = [_req(f"a{i}", float(i), range(1, 9), 2) for i in range(6)]
    res = _run_cluster(trace, n=3, placement="round_robin")
    assert [res.ledger[f"a{i}"]["replica"] for i in range(6)] == \
        ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_least_loaded_balances():
    # 4 simultaneous arrivals over 2 replicas: 2 land on each
    trace = [_req(f"b{i}", 0.0, range(1, 9), 6) for i in range(4)]
    res = _run_cluster(trace, n=2, placement="least_loaded")
    placed = [res.ledger[f"b{i}"]["replica"] for i in range(4)]
    assert placed.count("r0") == placed.count("r1") == 2


def test_prefix_aware_coplaces_sharers():
    rng = np.random.default_rng(0)
    pfx = [tuple(int(t) for t in rng.integers(1, 211, 16))
           for _ in range(2)]
    trace = []
    t = 0.0
    for i in range(8):
        c = i % 2
        tail = tuple(int(t_) for t_ in rng.integers(1, 211, 3))
        trace.append(_req(f"p{i}.k{c}", t, pfx[c] + tail, 3))
        t += 4.0  # spaced out: placement sees registered prefixes
    res = _run_cluster(trace, n=2, placement="prefix_aware")
    homes = {c: {res.ledger[r.rid]["replica"] for r in trace
                 if r.rid.endswith(f"k{c}")} for c in (0, 1)}
    # each cohort converges onto ONE replica...
    assert all(len(h) == 1 for h in homes.values()), homes
    # ...and the sharers actually hit its cache
    hits = {}
    for name, r in res.results.items():
        hits.update(r.prefix_cached)
    assert sum(1 for i in range(8) if hits[trace[i].rid] >= 16) == 6
    # cross-check the rollup counts them
    assert res.report()["prefill_tokens_saved"] > 0


def test_prefix_aware_falls_back_below_threshold():
    # nothing cached anywhere -> pure least-loaded placement
    trace = [_req(f"f{i}", 0.0, range(10 * i + 1, 10 * i + 9), 4)
             for i in range(4)]
    res = _run_cluster(trace, n=2, placement="prefix_aware")
    placed = [res.ledger[f"f{i}"]["replica"] for i in range(4)]
    assert placed.count("r0") == placed.count("r1") == 2


def test_make_placement_validates():
    with pytest.raises(ValueError, match="placement"):
        make_placement("best_effort")
    pol = make_placement("prefix_aware", 8)
    assert pol.threshold == 8 and pol.name == "prefix_aware"


# --- drain / join edge cases ------------------------------------------------

def test_drain_with_zero_inflight_removes_cleanly():
    trace = [_req("z0", 0.0, range(1, 9), 2)]
    # drain r1 long after r0 served everything: nothing to requeue
    res = _run_cluster(trace, n=2, placement="round_robin",
                       events=[(50.0, "drain", "r1")])
    ev = {e["event"]: e for e in res.events}
    assert ev["drain"]["requeued"] == []
    assert ev["remove"]["replica"] == "r1"
    assert ev["remove"]["census_ok"] is True
    cen = res.census()
    assert cen["conserved"] and cen["removal_census_ok"]
    assert cen["requeued"] == 0


def test_drain_under_overload_requeues_without_double_count():
    # one-slot replicas + a burst: the drained replica is mid-prefill
    # with a queue, which MUST move to the survivor and be counted once
    trace = [_req(f"o{i}", 0.0, range(1, 17), 8) for i in range(8)]
    res = _run_cluster(trace, n=2, placement="round_robin", slots=1,
                       events=[(6.0, "drain", "r0")])
    cen = res.census()
    assert cen["requeued"] >= 1
    assert cen["conserved"], cen
    assert cen["duplicated"] == [] and cen["lost"] == []
    per = cen["tenants"]["_none"]
    assert per["completed"] + per["shed"] == per["arrived"] == 8
    # requeued rids moved their whole metrics record: the drained
    # replica's collector no longer knows them
    drained = res.results["r0"]
    requeued = [rid for rid, led in res.ledger.items()
                if led["requeues"]]
    for rid in requeued:
        assert rid not in drained.outputs
        assert rid not in [v["rid"] for v
                           in drained.metrics.request_rows()]
    # in-flight work on r0 was NOT killed: it finished something
    assert drained.outputs
    ev = {e["event"]: e for e in res.events}
    assert ev["drain"]["in_flight"] >= 1
    assert ev["remove"]["census_ok"] is True


def test_join_mid_trace_gets_cold_cache_traffic():
    rng = np.random.default_rng(1)
    pfx = tuple(int(t) for t in rng.integers(1, 211, 16))
    trace = [_req(f"j{i}", float(i), pfx + (100 + i,), 3)
             for i in range(10)]
    res = _run_cluster(trace, n=1, placement="least_loaded",
                       events=[(4.5, "join", "r1")])
    joined = res.results["r1"]
    assert joined.outputs  # the joiner actually served traffic
    # its FIRST request found a cold cache (0 prefix tokens), later
    # sharers hit what it registered
    first = min(joined.prefix_cached,
                key=lambda rid: joined.metrics.request(rid)["arrival"])
    assert joined.prefix_cached[first] == 0
    assert res.census()["conserved"]


def test_cluster_replay_is_deterministic():
    trace = synthesize_cluster_trace(seed=7, n_requests=300,
                                     service_tokens_per_unit=8.0,
                                     vocab_size=211)
    ev = [(trace[120].arrival, "drain", "r0"),
          (trace[160].arrival, "join", "r2")]

    def one():
        res = _run_cluster(trace, n=2, placement="prefix_aware",
                           scheduler=16, events=ev)
        w = {"intl": 2.0, "std": 1.0, "bulk": 0.5}
        return (json.dumps(res.report(tenant_weights=w),
                           sort_keys=True),
                res.outputs(), res.events,
                {n: r.shed for n, r in res.results.items()})

    assert one() == one()  # byte-identical replay, lifecycle included


def test_drain_errors():
    trace = [_req("e0", 0.0, range(1, 9), 2)]
    with pytest.raises(ValueError, match="no live replica"):
        _run_cluster(trace, n=1, events=[(0.0, "drain", "r9")])
    with pytest.raises(RuntimeError, match="no admitting replica"):
        _run_cluster(trace, n=1, events=[(0.0, "drain", "r0")])
    with pytest.raises(ValueError, match="already live"):
        _run_cluster(trace, n=2, events=[(0.0, "join", "r1")])
    # rejoining a RETIRED name would overwrite its banked ServeResult
    # (every request it served would read as lost) — refused loudly
    with pytest.raises(ValueError, match="fresh name"):
        _run_cluster(trace, n=2, events=[(10.0, "drain", "r1"),
                                         (20.0, "join", "r1")])


# --- rollup / result surfaces -----------------------------------------------

def test_cluster_rollup_and_census():
    trace = synthesize_cluster_trace(seed=2, n_requests=400,
                                     service_tokens_per_unit=8.0,
                                     vocab_size=211)
    res = _run_cluster(trace, n=2, placement="prefix_aware",
                       scheduler=16)
    w = {"intl": 2.0, "std": 1.0, "bulk": 0.5}
    rep = res.report(tenant_weights=w)
    assert rep["arrived"] == 400
    assert rep["completed"] + rep["shed"] == 400
    assert rep["placement"] == "prefix_aware"
    assert set(rep["per_replica"]) == {"r0", "r1"}
    for pr in rep["per_replica"].values():
        assert pr["census_ok"] is True
    assert rep["prefill_tokens"] == sum(
        pr["prefill_tokens"] for pr in rep["per_replica"].values())
    assert rep["goodput_tokens"] <= rep["generated_tokens"]
    assert set(rep["tenants"]) == {"bulk", "intl", "std"}
    xs = [rep["tenants"][t]["goodput_tokens"] / w[t]
          for t in sorted(rep["tenants"])]
    assert rep["fairness_jain"] == jain_fairness(xs)
    cen = res.census()
    assert cen["conserved"] and cen["pool_census_ok"]
    # outputs() merges without collisions
    assert len(res.outputs()) == rep["completed"]


def test_router_runs_once():
    trace = [_req("x0", 0.0, range(1, 9), 2)]
    router = ClusterRouter(lambda name: _engine(), 1)
    router.run(trace)
    with pytest.raises(RuntimeError, match="runs once"):
        router.run(trace)


# --- the replica log field (satellite) --------------------------------------

def test_save_log_replica_field_roundtrip(tmp_path):
    trace = _mixed_trace(n=6)
    res = _engine().run(trace)
    plain = str(tmp_path / "plain.jsonl")
    res.save_log(plain)
    body = open(plain).read()
    assert '"replica"' not in body  # old format byte-identical
    loaded = load_engine_log(plain)
    assert all(len(t) == 4 for t in loaded["slot_log"])
    # the same result stamped as a replica tags EVERY record
    import dataclasses
    tagged = dataclasses.replace(res, replica="r3")
    tpath = str(tmp_path / "tagged.jsonl")
    tagged.save_log(tpath)
    for ln in open(tpath).read().splitlines():
        assert json.loads(ln)["replica"] == "r3"
    tl = load_engine_log(tpath)
    assert tl["meta"]["replica"] == "r3"
    assert all(len(t) == 5 and t[4] == "r3" for t in tl["slot_log"])
    # and the untagged fields round-trip identically either way
    assert [t[:4] for t in tl["slot_log"]] == loaded["slot_log"]
    assert [{k: v for k, v in d.items() if k != "replica"}
            for d in tl["decisions"]] == loaded["decisions"]


# --- per-replica trace report rows (satellite) ------------------------------

def test_cluster_trace_per_replica_rows(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from trace_report import (load_trace, replica_summaries,
                                  summarize, track_names,
                                  track_summaries)
    finally:
        sys.path.pop(0)
    out = str(tmp_path / "cluster_trace.json")
    trace = _mixed_trace(n=16)
    _run_cluster(trace, n=2, placement="least_loaded", trace_out=out)
    events = load_trace(out)
    tracks = track_names(events)
    reps = replica_summaries(events, tracks)
    assert [r["replica"] for r in reps] == ["r0", "r1"]
    for r in reps:
        assert r["slot_busy_frac"] > 0 and r["requests"] > 0
    # every root closed; global row still reads the cluster trace
    summ = summarize(events)
    assert summ["open_roots"] == [] and summ["requests"] == 16
    per_track = {r["track"]: r for r in track_summaries(events, tracks)}
    assert per_track["r0/engine"]["spans"] > 0
    # a LONE engine's trace yields no replica rows (no prefix)
    solo = str(tmp_path / "solo.json")
    _engine(trace=solo).run(trace)
    sev = load_trace(solo)
    assert replica_summaries(sev, track_names(sev)) == []


# --- the serving_cluster bench-gate family ----------------------------------

def _run_gate(text, tmp_path):
    env = {**os.environ,
           "BENCH_GATE_SERVING_BASELINE": str(tmp_path / "b.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "serving", "-"], input=text, capture_output=True, text=True,
        timeout=60, cwd=REPO, env=env)
    return r.returncode, [json.loads(ln) for ln in
                          r.stdout.strip().splitlines()]


def _cluster_row(placement, goodput, *, jain=0.6, saved=1000,
                 conserved=True, pools=True):
    return json.dumps({
        "bench": "serving_cluster", "placement": placement,
        "goodput_tokens_per_sec": goodput, "fairness_jain": jain,
        "prefill_tokens_saved": saved, "conserved": conserved,
        "pool_census_ok": pools, "arrived": 1000, "replicas": 4,
        "device": "sim"})


def _summary_row(parity=True):
    return json.dumps({"bench": "serving_cluster_summary",
                       "parity_ok": parity,
                       "parity_vs_oracle": {"round_robin": parity}})


def _life_row(conserved=True, requeued=3, removal=True, parity=True):
    return json.dumps({"bench": "serving_cluster_lifecycle",
                       "conserved": conserved, "requeued": requeued,
                       "removal_census_ok": removal,
                       "pool_census_ok": True,
                       "parity_vs_oracle": parity,
                       "lost": [], "duplicated": []})


def test_bench_gate_serving_cluster_family(tmp_path):
    base = [_cluster_row("round_robin", 10.0),
            _cluster_row("least_loaded", 10.5),
            _cluster_row("prefix_aware", 12.0, jain=0.65, saved=2000)]

    # pass: 1.2x goodput, fairness up, saved strictly greater
    rc, recs = _run_gate("\n".join(base + [_summary_row(),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 0 and recs[-1]["gate"] == "pass"
    assert recs[-1]["prefix_vs_round_robin_goodput"] == 1.2

    # sub-floor goodput FAILs naming the floor
    rows = [base[0], base[1],
            _cluster_row("prefix_aware", 11.0, saved=2000)]
    rc, recs = _run_gate("\n".join(rows + [_summary_row(),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "1.15" in json.dumps(recs[-1])

    # fairness traded away FAILs even with goodput
    rows = [base[0], base[1],
            _cluster_row("prefix_aware", 12.0, jain=0.3, saved=2000)]
    rc, recs = _run_gate("\n".join(rows + [_summary_row(),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "fairness" in recs[-1]["reason"]

    # saved must be STRICTLY greater
    rows = [base[0], base[1],
            _cluster_row("prefix_aware", 12.0, saved=1000)]
    rc, recs = _run_gate("\n".join(rows + [_summary_row(),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "co-placed" in recs[-1]["reason"]

    # parity divergence is correctness, not placement
    rc, recs = _run_gate("\n".join(base + [_summary_row(False),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "DIVERGING" in recs[-1]["reason"]

    # broken conservation on any placement row
    rows = [base[0], base[1],
            _cluster_row("prefix_aware", 12.0, saved=2000,
                         conserved=False)]
    rc, recs = _run_gate("\n".join(rows + [_summary_row(),
                                           _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "census" in recs[-1]["reason"]

    # lifecycle row: missing -> FAIL; requeued==0 -> FAIL (the drain
    # never exercised the requeue path the invariant is about)
    rc, recs = _run_gate("\n".join(base + [_summary_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "lifecycle" in recs[-1]["reason"]
    rc, recs = _run_gate("\n".join(base + [
        _summary_row(), _life_row(requeued=0)]) + "\n", tmp_path)
    assert rc == 1 and "requeued" in recs[-1]["reason"]

    # missing prefix_aware row -> graceful FAIL, never a traceback
    rc, recs = _run_gate("\n".join(base[:2] + [_summary_row(),
                                               _life_row()]) + "\n",
                         tmp_path)
    assert rc == 1 and "prefix_aware" in recs[-1]["reason"]

    # a cluster FAIL must not be masked by a passing qos family: the
    # combined verdict is the last record
    qos = [json.dumps({"bench": "serving_qos", "scheduler": s,
                       "goodput_tokens_per_sec": g,
                       "slo_tight_attained": 1.0, "tight_requests": 5,
                       "deadline_hits": 5, "completed": 10, "shed": 0,
                       "arrived": 10, "device": "cpu"})
           for s, g in (("fifo", 1.0), ("qos", 1.6))]
    rows = [base[0], base[1],
            _cluster_row("prefix_aware", 11.0, saved=2000)]
    rc, recs = _run_gate("\n".join(qos + rows + [
        _summary_row(), _life_row()]) + "\n", tmp_path)
    assert rc == 1
    assert recs[-1]["combined"] is True
    assert recs[-1]["qos_gate"] == "pass"
    assert recs[-1]["cluster_gate"] == "FAIL"


# --- real-model smoke -------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


def test_cluster_matches_lone_engine_on_real_model(tiny_model):
    """2 real-factory replicas vs one lone engine: every request's
    greedy stream identical — placement is bookkeeping, never math.
    Each replica gets its OWN factory (pool buffers are per-factory)."""
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)

    def factory():
        return llama_serving_decode_factory(
            tiny_model, max_len=48, page_size=8, n_pool_pages=13,
            batch_capacity=2, chunked_prefill=8)

    trace = synthesize_trace(seed=5, n_requests=6, arrival="poisson",
                             mean_interarrival=1.0, prompt_len=(4, 10),
                             output_len=(2, 4), vocab_size=97,
                             rid_prefix="rm")

    def spawn(name):
        return ServingEngine(serving=factory(), slots=2,
                             policy="paged", clock="fixed",
                             fixed_costs=COSTS)

    res = ClusterRouter(spawn, 2, placement="least_loaded").run(trace)
    lone = ServingEngine(serving=factory(), slots=2, policy="paged",
                         clock="fixed", fixed_costs=COSTS).run(trace)
    assert res.outputs() == lone.outputs
    assert res.census()["conserved"]
