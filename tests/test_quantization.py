"""Quantization toolkit: QAT (STE), observers, PTQ -> int8 execution.

~ reference slim tests (test_post_training_quantization_*.py,
test_imperative_qat.py): calibrate on data, quantize, assert the
quantized model stays close to the fp32 oracle.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    AbsMaxObserver, HistObserver, ImperativeQuantAware, Int8Linear,
    PostTrainingQuantization, convert_to_int8, quantize_weight_per_channel)


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


class TestObservers:
    def test_abs_max(self):
        obs = AbsMaxObserver()
        obs.update(np.array([1.0, -3.0]))
        obs.update(np.array([2.0]))
        assert obs.scale() == 3.0

    def test_hist_percentile_ignores_outlier(self):
        obs = HistObserver(bins=256, percentile=0.99)
        rng = np.random.default_rng(0)
        obs.update(rng.normal(0, 1.0, 10000))
        obs.update(np.array([50.0]))  # single outlier
        # percentile scale should sit near the bulk, far below the outlier
        assert obs.scale() < 10.0

    def test_hist_range_stretch(self):
        obs = HistObserver(bins=64)
        obs.update(np.linspace(0, 1, 100))
        obs.update(np.linspace(0, 4, 100))  # wider range rebins
        assert 0 < obs.scale() <= 4.0

    def test_kl(self):
        obs = HistObserver(bins=512, algo="KL")
        rng = np.random.default_rng(1)
        obs.update(rng.normal(0, 1.0, 20000))
        s = obs.scale()
        assert 0.5 < s < 6.0


class TestWeightQuant:
    def test_per_channel_roundtrip(self):
        rng = np.random.default_rng(0)
        w = rng.normal(0, 1, (8, 4)).astype(np.float32)
        w[:, 2] *= 100.0  # one large-magnitude channel
        q, s = quantize_weight_per_channel(w, axis=1)
        assert q.dtype == np.int8 and s.shape == (1, 4)
        deq = q.astype(np.float32) * s
        # per-channel scales keep small channels accurate despite channel 2
        per_chan_err = np.abs(deq - w).max(axis=0)
        per_chan_bound = np.abs(w).max(axis=0) / 100
        assert (per_chan_err <= per_chan_bound).all(), per_chan_err


class TestQAT:
    def test_ste_gradients_flow(self):
        m = ImperativeQuantAware().quantize(_mlp())
        m.train()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(0, 1, (4, 16)).astype(np.float32))
        loss = m(x).mean()
        loss.backward()
        lin = m[0].inner
        g = lin.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()
        assert np.abs(g.numpy()).sum() > 0  # straight-through, not zeroed


class TestPTQ:
    @pytest.mark.parametrize("algo", ["abs_max", "avg", "hist", "KL"])
    def test_int8_close_to_fp32(self, algo):
        rng = np.random.default_rng(0)
        m = _mlp()
        m.eval()
        x = rng.normal(0, 1, (32, 16)).astype(np.float32)
        ref = m(paddle.to_tensor(x)).numpy()
        loader = [paddle.to_tensor(x[i:i + 8]) for i in range(0, 32, 8)]
        ptq = PostTrainingQuantization(m, loader, algo=algo)
        qm = ptq.quantize()
        assert isinstance(qm[0], Int8Linear)
        assert qm[0].act_scale is not None  # static calibrated scale
        out = qm(paddle.to_tensor(x)).numpy()
        # mean error: all algos must track the fp32 oracle closely; max
        # error additionally bounded loosely because avg/KL clip outliers
        # by design
        mean_rel = np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-8)
        max_rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert mean_rel < 0.1, f"{algo}: mean deviation {mean_rel:.3f}"
        assert max_rel < 0.5, f"{algo}: max deviation {max_rel:.3f}"

    def test_save_quantized_model(self, tmp_path):
        rng = np.random.default_rng(0)
        m = _mlp()
        m.eval()
        loader = [paddle.to_tensor(
            rng.normal(0, 1, (8, 16)).astype(np.float32))]
        ptq = PostTrainingQuantization(m, loader)
        ptq.quantize()
        state = ptq.save_quantized_model(str(tmp_path / "q"))
        int8_keys = [k for k in state if k.endswith("weight_int8")]
        assert len(int8_keys) == 2
        assert all(state[k].dtype == np.int8 for k in int8_keys)

    def test_dynamic_fallback(self):
        # convert without calibration -> dynamic activation scales
        m = _mlp()
        m.eval()
        x = np.random.default_rng(0).normal(0, 1, (4, 16)).astype(np.float32)
        ref = m(paddle.to_tensor(x)).numpy()
        qm = convert_to_int8(m)
        assert qm[0].act_scale is None
        out = qm(paddle.to_tensor(x)).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert rel < 0.1


class TestActScalePlumbing:
    """The shared int8 GEMM's activation-scale plumbing (the same
    kernel the int8 serving KV tier and compiled decode ride):
    ``int8_matmul(act_scale=)`` must honor a calibrated static scale
    exactly, and ``convert_to_int8(act_scales=)`` must deliver scales
    to NESTED sublayers by dotted path."""

    def test_static_scale_matches_dynamic_at_absmax(self):
        import jax.numpy as jnp

        from paddle_tpu.quantization.int8 import QMAX, int8_matmul
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (4, 16)).astype(np.float32)
        w = rng.normal(0, 1, (16, 8)).astype(np.float32)
        q, s = quantize_weight_per_channel(w, axis=1)
        dyn = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                                     jnp.asarray(s[0])))
        # a static scale equal to the dynamic rule's abs-max scale is
        # the SAME quantization: bit-equal outputs
        sx = float(np.abs(x).max()) / QMAX
        stat = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                                      jnp.asarray(s[0]),
                                      act_scale=sx))
        assert (dyn == stat).all()
        # a different calibrated scale changes the grid: the argument
        # is live, not decorative
        other = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                                       jnp.asarray(s[0]),
                                       act_scale=sx / 4))
        assert not (other == dyn).all()

    def test_int8_matmul_error_bound(self):
        import jax.numpy as jnp

        from paddle_tpu.quantization.int8 import int8_matmul
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (8, 32)).astype(np.float32)
        w = rng.normal(0, 1, (32, 16)).astype(np.float32)
        q, s = quantize_weight_per_channel(w, axis=1)
        out = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                                     jnp.asarray(s[0])))
        ref = x @ w
        max_rel = np.abs(out - ref).max() / np.abs(ref).max()
        mean_rel = np.abs(out - ref).mean() / np.abs(ref).mean()
        assert max_rel < 0.06
        assert mean_rel < 0.02

    def test_convert_act_scales_nested_paths(self):
        m = nn.Sequential(nn.Linear(16, 8), nn.ReLU(),
                          nn.Sequential(nn.Linear(8, 4)))
        m.eval()
        x = np.random.default_rng(2).normal(
            0, 1, (4, 16)).astype(np.float32)
        ref = m(paddle.to_tensor(x)).numpy()
        qm = convert_to_int8(m, act_scales={"2.0": 0.05})
        # the nested layer got its calibrated scale by dotted path;
        # the un-calibrated top-level layer fell back to dynamic
        assert qm[0].act_scale is None
        assert qm[2][0].act_scale == 0.05
        assert isinstance(qm[2][0], Int8Linear)
        out = qm(paddle.to_tensor(x)).numpy()
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-8)
        assert rel < 0.15
