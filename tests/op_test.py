"""OpTest harness: numpy-oracle forward checks + numeric gradient checks.

~ python/paddle/fluid/tests/unittests/op_test.py:292 (check_output:1728,
check_grad:1817 — central finite differences vs analytic grads). Runs on
the CPU backend in float64-capable mode for tight tolerances.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(api: Callable, inputs: Sequence[np.ndarray], expected,
                 attrs: dict | None = None, atol=1e-5, rtol=5e-4):
    """Run the eager op on Tensor inputs and compare with numpy oracle."""
    attrs = attrs or {}
    t_in = [paddle.to_tensor(x) if isinstance(x, np.ndarray) else x
            for x in inputs]
    out = api(*t_in, **attrs)
    if isinstance(expected, (list, tuple)):
        assert isinstance(out, (list, tuple)), f"expected multi-output"
        for o, e in zip(out, expected):
            np.testing.assert_allclose(np.asarray(o._value), e, atol=atol,
                                       rtol=rtol)
    else:
        np.testing.assert_allclose(np.asarray(out._value), expected,
                                   atol=atol, rtol=rtol)
    return out


def check_grad(api: Callable, inputs: Sequence[np.ndarray],
               grad_inputs: Sequence[int] | None = None,
               attrs: dict | None = None, delta=1e-3, atol=1e-2, rtol=1e-2,
               output_index=None):
    """Numeric finite-difference grad check (~ op_test.py check_grad:1817).

    Builds scalar loss = sum(op(inputs)) and compares tape gradients against
    central differences computed in float64 numpy.
    """
    attrs = attrs or {}
    if grad_inputs is None:
        grad_inputs = [i for i, x in enumerate(inputs)
                       if isinstance(x, np.ndarray)
                       and np.issubdtype(x.dtype, np.floating)]

    def run_loss(np_inputs):
        t_in = [paddle.to_tensor(x.astype(np.float32), stop_gradient=False)
                if isinstance(x, np.ndarray)
                and np.issubdtype(x.dtype, np.floating)
                else (paddle.to_tensor(x) if isinstance(x, np.ndarray) else x)
                for x in np_inputs]
        out = api(*t_in, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[output_index if output_index is not None else 0]
        return out, t_in

    # analytic grads via tape
    out, t_in = run_loss(inputs)
    loss = out.sum() if out.size > 1 else out
    loss.backward()
    analytic = {}
    for i in grad_inputs:
        g = t_in[i].grad
        assert g is not None, f"no grad for input {i}"
        analytic[i] = np.asarray(g._value, dtype=np.float64)

    # numeric central differences
    for i in grad_inputs:
        x = np.asarray(inputs[i], dtype=np.float64)
        num = np.zeros_like(x)
        flat = x.reshape(-1)
        num_flat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + delta
            plus, _ = run_loss([x.astype(np.float32) if k == i else v
                                for k, v in enumerate(inputs)])
            lp = float(np.asarray(
                (plus.sum() if plus.size > 1 else plus)._value))
            flat[j] = orig - delta
            minus, _ = run_loss([x.astype(np.float32) if k == i else v
                                 for k, v in enumerate(inputs)])
            lm = float(np.asarray(
                (minus.sum() if minus.size > 1 else minus)._value))
            flat[j] = orig
            num_flat[j] = (lp - lm) / (2 * delta)
        np.testing.assert_allclose(
            analytic[i], num, atol=atol, rtol=rtol,
            err_msg=f"grad mismatch for input {i} of {api}")
