"""BERT pretrain step factory (BASELINE config 3 path).

~ reference PaddleNLP BERT pretraining recipe shape: compiled DP train
step, masked-LM ignore_index semantics, loss decreases.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture
def setup():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.models.nlp import (BertConfig, BertForPretraining,
                                       bert_pretrain_step_factory)
    paddle.seed(0)
    cfg = BertConfig.tiny()
    model = BertForPretraining(cfg)
    model.eval()
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
    params, opt, step = bert_pretrain_step_factory(model, mesh,
                                                   learning_rate=1e-3)
    rng = np.random.default_rng(0)
    B, S = 4, 16
    batch = dict(
        ids=jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32),
        types=jnp.zeros((B, S), jnp.int32),
        mlm=jnp.asarray(np.where(rng.random((B, S)) < 0.15,
                                 rng.integers(0, cfg.vocab_size, (B, S)),
                                 -100), jnp.int32),
        nsp=jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32))
    return params, opt, step, batch


def test_loss_decreases(setup):
    params, opt, step, b = setup
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, b["ids"], b["types"],
                                 b["mlm"], b["nsp"])
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_ignore_index_masks_mlm(setup):
    import jax.numpy as jnp
    params, opt, step, b = setup
    # all labels ignored -> only the NSP term remains (~ln 2 at init)
    all_ignored = jnp.full_like(b["mlm"], -100)
    _, _, loss = step(params, opt, b["ids"], b["types"], all_ignored,
                      b["nsp"])
    assert float(loss) < 2.0  # no V-way CE term (ln(30522) ~ 10.3)
