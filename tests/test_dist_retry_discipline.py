"""The dist-test retry machinery itself (~ dist_test.sh discipline)."""
import os

import pytest

_attempts = {"n": 0}  # process-local: no cross-run or cross-worker state


@pytest.mark.dist_retry(n=1)
def test_retry_reruns_failed_attempt():
    """Fails on the first attempt, passes on the rerun — the marked
    protocol must absorb exactly that pattern."""
    _attempts["n"] += 1
    assert _attempts["n"] >= 2, "first attempt fails by design"


def test_quarantine_file_is_documented():
    path = os.path.join(os.path.dirname(__file__), "quarantine.txt")
    assert os.path.exists(path)
    with open(path) as f:
        active = [ln for ln in f
                  if ln.strip() and not ln.startswith("#")]
    # the list must stay empty unless a line carries an issue reference
    assert all("#" in ln for ln in active), active
