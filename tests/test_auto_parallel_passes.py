"""Auto-parallel program transformation: Completer / Partitioner /
Resharder golden tests.

~ reference auto_parallel tests (SURVEY.md §4): build a serial program,
run completion + partition + reshard, and assert on the GENERATED PROGRAM
TEXT per rank — ops, dist attrs, local shapes, inserted communication.
Refs: completion.py:139, partitioner.py:37, reshard.py:603.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed.auto_parallel import (Completer, Partitioner,
                                                  ProcessMesh)


@pytest.fixture
def mlp_program():
    paddle.enable_static()
    import paddle_tpu.nn.functional as F
    x = static.data("x", [8, 16], "float32")
    h = static.nn.fc(x, 16, name="fc1")
    r = F.relu(h)
    o = static.nn.fc(r, 4, name="fc2")
    loss = paddle.mean(o)
    yield x, h, r, o, loss
    paddle.disable_static()


def _param_names(loss):
    # walk producers, collect Parameter arg names in deterministic order
    names, seen, stack = [], set(), [loss]
    while stack:
        v = stack.pop()
        node = getattr(v, "_node", None)
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        for a in node.args:
            if getattr(a, "persistable", False):
                names.append(a.name)
            elif hasattr(a, "_node"):
                stack.append(a)
    return names


class TestCompleter:
    def test_mp_propagation_marks_partial_and_allreduce(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        # fc1 weight column-parallel (out dim over mp), fc2 weight
        # row-parallel (in dim over mp) — Megatron MLP split
        w1, b1 = params[-2], params[-1]  # reverse topo: fc2 first
        w2 = params[0]
        ann = {"x": [None, None],
               w1: [None, "mp"], b1: ["mp"],
               w2: ["mp", None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)

        names = [op.op_name for op in ctx.ops]
        assert names == ["linear", "relu", "linear", "mean"]
        # fc1 out sharded over mp (axis 1) on its last dim
        assert ctx.ops[0].out_attrs[0].dims_mapping == [-1, 1]
        # relu preserves the sharding
        assert ctx.ops[1].out_attrs[0].dims_mapping == [-1, 1]
        # fc2 contracts the mp-sharded dim on both sides -> partial sum
        assert ctx.ops[2].out_attrs[0].dims_mapping == [-1, -1]
        assert ctx.ops[2].out_attrs[0].is_partial_on == frozenset({1})

    def test_dp_batch_annotation(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        ctx = Completer(mesh, {"x": ["dp", None]}) \
            .complete_forward_annotation(loss)
        # batch dim stays dp-sharded through the stack
        assert ctx.ops[0].out_attrs[0].dims_mapping == [0, -1]
        assert ctx.ops[2].out_attrs[0].dims_mapping == [0, -1]


class TestPartitionerGolden:
    def test_rank_program_text(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        w1, b1, w2 = params[-2], params[-1], params[0]
        ann = {"x": ["dp", None],
               w1: [None, "mp"], b1: ["mp"],
               w2: ["mp", None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)
        text = Partitioner(ctx).partition(0)
        lines = [ln.strip() for ln in text.splitlines()]

        # golden: local shapes halve over dp (batch 8->4) and mp (16->8)
        assert lines[0].startswith("rank 0 coords {'dp': 0, 'mp': 0}")
        assert any(ln.startswith("linear(x[4, 16]") and "[16, 8]" in ln
                   for ln in lines), text
        assert any(ln.startswith("relu") and "[4, 8]" in ln
                   for ln in lines), text
        # the partial sum from the row-parallel fc2 resolves with an
        # inserted c_allreduce_sum over the mp mesh dim before mean's
        # replicated requirement... mean keeps partial over mp AND dp
        assert any("c_allreduce_sum" in ln and "'mp'" in ln
                   for ln in lines), text

    def test_reshard_allgather_inserted_on_mismatch(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        w1, b1, w2 = params[-2], params[-1], params[0]
        # fc1 column-parallel but fc2 NOT row-parallel: the mp-sharded
        # activation must be all-gathered before entering fc2
        ann = {w1: [None, "mp"], b1: ["mp"], w2: [None, None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)
        text = Partitioner(ctx).partition(2)
        assert "c_allgather" in text and "'mp'" in text, text

    def test_partition_all_covers_every_rank(self, mlp_program):
        *_, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        ctx = Completer(mesh, {"x": ["dp", None]}) \
            .complete_forward_annotation(loss)
        progs = Partitioner(ctx).partition_all()
        assert sorted(progs) == [0, 1, 2, 3]
        assert progs[1] != progs[0]  # coords differ in the header


class TestPassEffectsMaterialize:
    """VERDICT r3 item 9: a strategy-flip pass must be visible in the
    COMPILED program, not just in the strategy object — remat changes the
    backward's op mix, sharding makes 1/N moment shards, amp puts bf16 on
    the MXU ops, gradient-merge keeps one collective per k microbatches."""

    def _ctx(self, **kw):
        from paddle_tpu.distributed.passes import PassContext
        return PassContext(**kw)

    def test_recompute_pass_changes_compiled_backward(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed.passes import PassManager, new_pass

        ctx = self._ctx()
        PassManager([new_pass("auto_parallel_recompute")]).apply(ctx)
        assert ctx.strategy.recompute

        w1 = jnp.ones((32, 32))
        w2 = jnp.ones((32, 32))
        x = jnp.ones((4, 32))

        def loss(w1, w2, x, remat):
            def body(x):
                return jnp.tanh(x @ w1) @ w2
            f = jax.checkpoint(body) if remat else body
            return f(x).sum()

        def barriers(remat):
            g = jax.grad(lambda a, b: loss(a, b, x, remat), argnums=(0, 1))
            txt = jax.jit(g).lower(w1, w2).as_text()
            return txt.count("optimization_barrier")

        # the pass's effect (remat=strategy.recompute) must materialize:
        # jax.checkpoint lowers to an optimization_barrier that pins the
        # recompute into the backward (absent without the pass)
        assert barriers(ctx.strategy.recompute) > barriers(False) == 0

    def test_sharding_pass_moments_are_one_nth(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.passes import PassManager, new_pass
        from paddle_tpu.models.nlp.train_utils import make_adamw_state

        class _Opt:
            pass

        ctx = self._ctx(optimizer=_Opt())
        PassManager([new_pass("auto_parallel_sharding",
                              {"stage": 1, "degree": 8})]).apply(ctx)
        axis = ctx.optimizer._shard_states_axis
        assert axis == "sharding"

        mesh = Mesh(np.asarray(jax.devices()[:8]), (axis,))
        params = {"w": jax.device_put(
            jnp.zeros((64, 16)), NamedSharding(mesh, P(None, None)))}
        shardings = {"w": NamedSharding(mesh, P(None, None))}
        state = make_adamw_state(mesh, shardings, params, jnp.float32)
        m = state["m"]["w"]
        # the ZeRO contract the pass promises: every moment shard is 1/N
        assert m.addressable_shards[0].data.size * 8 == m.size

    def test_amp_pass_bf16_reaches_the_dot(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed.passes import PassManager, new_pass
        from paddle_tpu.amp import auto_cast

        ctx = self._ctx()
        PassManager([new_pass("auto_parallel_amp",
                              {"dtype": "bfloat16"})]).apply(ctx)
        assert ctx.strategy.amp_configs["dtype"] == "bfloat16"

        import paddle_tpu as paddle
        x = paddle.to_tensor(jnp.ones((8, 8), jnp.float32))

        def fwd(x):
            with auto_cast(enable=ctx.strategy.amp,
                           dtype=ctx.strategy.amp_configs["dtype"]):
                return paddle.matmul(x, x)

        txt = jax.jit(lambda a: fwd(paddle.Tensor(a))._value).lower(
            x._value).compile().as_text()
        assert "bf16" in txt, "amp pass did not reach the compiled dot"

    def test_gradient_merge_one_collective_per_k(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.passes import PassManager, new_pass

        ctx = self._ctx()
        PassManager([new_pass("auto_parallel_gradient_merge",
                              {"k_steps": 4})]).apply(ctx)
        k = ctx.strategy.gradient_merge_configs["k_steps"]

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
        w = jax.device_put(jnp.ones((16, 16)), NamedSharding(mesh, P()))
        xs = jax.device_put(jnp.ones((k, 8, 16)),
                            NamedSharding(mesh, P(None, "data")))

        def merged_step(w, xs):
            def micro(acc, x):
                g = jax.grad(lambda w: jnp.tanh(x @ w).sum())(w)
                return acc + g, None
            acc, _ = jax.lax.scan(micro, jnp.zeros_like(w), xs)
            return w - 0.1 * acc / k  # ONE update per k microbatches

        txt = jax.jit(merged_step).lower(w, xs).compile().as_text()
        n_ar = txt.count("all-reduce(") + txt.count("all-reduce-start(")
        # the merge boundary is the ONLY gradient collective — k
        # microbatches must not produce k all-reduces
        assert 1 <= n_ar < k, f"{n_ar} all-reduces for k={k}"
