"""Auto-parallel program transformation: Completer / Partitioner /
Resharder golden tests.

~ reference auto_parallel tests (SURVEY.md §4): build a serial program,
run completion + partition + reshard, and assert on the GENERATED PROGRAM
TEXT per rank — ops, dist attrs, local shapes, inserted communication.
Refs: completion.py:139, partitioner.py:37, reshard.py:603.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed.auto_parallel import (Completer, Partitioner,
                                                  ProcessMesh)


@pytest.fixture
def mlp_program():
    paddle.enable_static()
    import paddle_tpu.nn.functional as F
    x = static.data("x", [8, 16], "float32")
    h = static.nn.fc(x, 16, name="fc1")
    r = F.relu(h)
    o = static.nn.fc(r, 4, name="fc2")
    loss = paddle.mean(o)
    yield x, h, r, o, loss
    paddle.disable_static()


def _param_names(loss):
    # walk producers, collect Parameter arg names in deterministic order
    names, seen, stack = [], set(), [loss]
    while stack:
        v = stack.pop()
        node = getattr(v, "_node", None)
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        for a in node.args:
            if getattr(a, "persistable", False):
                names.append(a.name)
            elif hasattr(a, "_node"):
                stack.append(a)
    return names


class TestCompleter:
    def test_mp_propagation_marks_partial_and_allreduce(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        # fc1 weight column-parallel (out dim over mp), fc2 weight
        # row-parallel (in dim over mp) — Megatron MLP split
        w1, b1 = params[-2], params[-1]  # reverse topo: fc2 first
        w2 = params[0]
        ann = {"x": [None, None],
               w1: [None, "mp"], b1: ["mp"],
               w2: ["mp", None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)

        names = [op.op_name for op in ctx.ops]
        assert names == ["linear", "relu", "linear", "mean"]
        # fc1 out sharded over mp (axis 1) on its last dim
        assert ctx.ops[0].out_attrs[0].dims_mapping == [-1, 1]
        # relu preserves the sharding
        assert ctx.ops[1].out_attrs[0].dims_mapping == [-1, 1]
        # fc2 contracts the mp-sharded dim on both sides -> partial sum
        assert ctx.ops[2].out_attrs[0].dims_mapping == [-1, -1]
        assert ctx.ops[2].out_attrs[0].is_partial_on == frozenset({1})

    def test_dp_batch_annotation(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        ctx = Completer(mesh, {"x": ["dp", None]}) \
            .complete_forward_annotation(loss)
        # batch dim stays dp-sharded through the stack
        assert ctx.ops[0].out_attrs[0].dims_mapping == [0, -1]
        assert ctx.ops[2].out_attrs[0].dims_mapping == [0, -1]


class TestPartitionerGolden:
    def test_rank_program_text(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        w1, b1, w2 = params[-2], params[-1], params[0]
        ann = {"x": ["dp", None],
               w1: [None, "mp"], b1: ["mp"],
               w2: ["mp", None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)
        text = Partitioner(ctx).partition(0)
        lines = [ln.strip() for ln in text.splitlines()]

        # golden: local shapes halve over dp (batch 8->4) and mp (16->8)
        assert lines[0].startswith("rank 0 coords {'dp': 0, 'mp': 0}")
        assert any(ln.startswith("linear(x[4, 16]") and "[16, 8]" in ln
                   for ln in lines), text
        assert any(ln.startswith("relu") and "[4, 8]" in ln
                   for ln in lines), text
        # the partial sum from the row-parallel fc2 resolves with an
        # inserted c_allreduce_sum over the mp mesh dim before mean's
        # replicated requirement... mean keeps partial over mp AND dp
        assert any("c_allreduce_sum" in ln and "'mp'" in ln
                   for ln in lines), text

    def test_reshard_allgather_inserted_on_mismatch(self, mlp_program):
        x, h, r, o, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        params = _param_names(loss)
        w1, b1, w2 = params[-2], params[-1], params[0]
        # fc1 column-parallel but fc2 NOT row-parallel: the mp-sharded
        # activation must be all-gathered before entering fc2
        ann = {w1: [None, "mp"], b1: ["mp"], w2: [None, None]}
        ctx = Completer(mesh, ann).complete_forward_annotation(loss)
        text = Partitioner(ctx).partition(2)
        assert "c_allgather" in text and "'mp'" in text, text

    def test_partition_all_covers_every_rank(self, mlp_program):
        *_, loss = mlp_program
        mesh = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        ctx = Completer(mesh, {"x": ["dp", None]}) \
            .complete_forward_annotation(loss)
        progs = Partitioner(ctx).partition_all()
        assert sorted(progs) == [0, 1, 2, 3]
        assert progs[1] != progs[0]  # coords differ in the header
