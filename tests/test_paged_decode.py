"""llama_paged_decode_factory: compiled continuous-batching decode over
the paged KV pool must reproduce the eager model's greedy tokens — per
sequence, at RAGGED lengths in one batch."""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.nlp.llama_decode import llama_paged_decode_factory
from paddle_tpu.ops.pallas.paged_attention import PagedKVCache

PS = 8  # page size


def _greedy_eager(model, prompt, n):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n)
    return np.asarray(out.numpy())[0, len(prompt):]


def test_paged_decode_matches_eager_ragged():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    outer, layers, pools, prefill, decode_step, _ = \
        llama_paged_decode_factory(model, page_size=PS, n_pool_pages=16)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 64, 5).tolist(),
               rng.integers(1, 64, 3).tolist()]
    lengths = np.asarray([len(p) for p in prompts], np.int32)
    T = PS  # pad prompts to one page
    toks = np.zeros((2, T), np.int64)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p

    # host page bookkeeping: 3 pages per sequence (room for 19 tokens)
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2, head_dim=8)
    for i in range(2):
        book.allocate(i, 3 * PS)
    pt = jnp.asarray(np.stack([book.tables[0], book.tables[1]]),
                     jnp.int32)

    N = 6
    nxt, pools = prefill(outer, layers, jnp.asarray(toks), pt,
                         jnp.asarray(lengths), pools)
    got = [np.asarray(nxt)]
    lens = jnp.asarray(lengths)
    for _ in range(N - 1):
        nxt, pools = decode_step(outer, layers, nxt, pt, lens, pools)
        lens = lens + 1
        got.append(np.asarray(nxt))
    got = np.stack(got, 1)  # (B, N)

    for i, p in enumerate(prompts):
        want = _greedy_eager(model, p, N)
        np.testing.assert_array_equal(
            got[i], want, err_msg=f"sequence {i}")


def test_paged_decode_crosses_page_boundary():
    """Decode past a page edge: token PS lands in the second page and
    attention still sees the whole history."""
    paddle.seed(1)
    cfg = LlamaConfig.tiny(vocab=32, hidden=32, layers=1, heads=2,
                           kv_heads=1)
    model = LlamaForCausalLM(cfg)
    outer, layers, pools, prefill, decode_step, _ = \
        llama_paged_decode_factory(model, page_size=PS, n_pool_pages=8)
    prompt = list(range(1, PS))  # length 7: boundary hits mid-decode
    book = PagedKVCache(n_pages=8, page_size=PS, kv_heads=1, head_dim=16)
    book.allocate(0, 2 * PS)
    pt = jnp.asarray([book.tables[0]], jnp.int32)
    toks = jnp.asarray(np.asarray(prompt + [0])[None])
    lens = jnp.asarray([len(prompt)], jnp.int32)

    N = 5  # positions 7..11 — crosses into page 2 at position 8
    nxt, pools = prefill(outer, layers, toks, pt, lens, pools)
    got = [int(nxt[0])]
    for _ in range(N - 1):
        nxt, pools = decode_step(outer, layers, nxt, pt, lens, pools)
        lens = lens + 1
        got.append(int(nxt[0]))

    want = _greedy_eager(model, prompt, N)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_chunked_prefill_matches_oneshot():
    """Chunked prefill (C-token chunks attending through the pool) must
    produce the same next token and the same subsequent decode stream as
    the one-shot prefill."""
    paddle.seed(2)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    o1, l1, pools1, prefill1, decode1, *_ = factory(model, page_size=PS,
                                                n_pool_pages=16)
    o2, l2, pools2, prefill2, decode2, *_ = factory(model, page_size=PS,
                                                n_pool_pages=16,
                                                chunked_prefill=PS)
    # chunk = 2 pages: exercises the multi-page scatter (npg > 1)
    o3, l3, pools3, prefill3, decode3, *_ = factory(model, page_size=PS,
                                                n_pool_pages=16,
                                                chunked_prefill=2 * PS)

    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 64, 14).tolist(),
               rng.integers(1, 64, 9).tolist()]
    lengths = jnp.asarray([len(p) for p in prompts], jnp.int32)
    T = 2 * PS  # two chunks
    toks = np.zeros((2, T), np.int64)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2, head_dim=8)
    for i in range(2):
        book.allocate(i, 3 * PS)
    pt = jnp.asarray(np.stack([book.tables[0], book.tables[1]]),
                     jnp.int32)

    n1, pools1 = prefill1(o1, l1, jnp.asarray(toks), pt, lengths, pools1)
    n2, pools2 = prefill2(o2, l2, jnp.asarray(toks), pt, lengths, pools2)
    n3, pools3 = prefill3(o3, l3, jnp.asarray(toks), pt, lengths, pools3)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n3))
    lens = lengths
    for _ in range(4):
        n1, pools1 = decode1(o1, l1, n1, pt, lens, pools1)
        n2, pools2 = decode2(o2, l2, n2, pt, lens, pools2)
        lens = lens + 1
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))


def test_int8_pool_decode_close_to_fp():
    """kv_cache_dtype='int8' on the paged path: greedy tokens match the
    fp pools on a short horizon (the dense cache's int8 bar) and the
    pools really store int8."""
    paddle.seed(5)
    cfg = LlamaConfig.tiny(vocab=64, hidden=64, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    mk = lambda **kw: factory(model, page_size=PS, n_pool_pages=16, **kw)
    o1, l1, pools_f, pre_f, dec_f, *_ = mk()
    o2, l2, pools_q, pre_q, dec_q, *_ = mk(kv_cache_dtype="int8")
    assert pools_q[0][0].dtype == jnp.int8

    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, 64, 6).tolist(),
               rng.integers(1, 64, 4).tolist()]
    lengths = jnp.asarray([len(p) for p in prompts], jnp.int32)
    toks = np.zeros((2, PS), np.int64)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2,
                        head_dim=16)
    for i in range(2):
        book.allocate(i, 2 * PS)
    pt = jnp.asarray(np.stack([book.tables[0], book.tables[1]]),
                     jnp.int32)

    nf, pools_f = pre_f(o1, l1, jnp.asarray(toks), pt, lengths, pools_f)
    nq, pools_q = pre_q(o2, l2, jnp.asarray(toks), pt, lengths, pools_q)
    np.testing.assert_array_equal(np.asarray(nf), np.asarray(nq))
    lens = lengths
    for _ in range(5):
        nf, pools_f = dec_f(o1, l1, nf, pt, lens, pools_f)
        nq, pools_q = dec_q(o2, l2, nq, pt, lens, pools_q)
        lens = lens + 1
        np.testing.assert_array_equal(np.asarray(nf), np.asarray(nq))


def test_emit_logits_mode():
    """emit='logits': the serving loop owns sampling; argmax over the
    emitted logits must reproduce the token-mode stream."""
    paddle.seed(6)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    o1, l1, p1, pre_t, dec_t, *_ = factory(model, page_size=PS,
                                       n_pool_pages=16)
    o2, l2, p2, pre_l, dec_l, *_ = factory(model, page_size=PS,
                                       n_pool_pages=16, emit="logits")

    rng = np.random.default_rng(6)
    toks = np.zeros((1, PS), np.int64)
    toks[0, :5] = rng.integers(1, 64, 5)
    lens = jnp.asarray([5], jnp.int32)
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2, head_dim=8)
    book.allocate(0, 2 * PS)
    pt = jnp.asarray([book.tables[0]], jnp.int32)

    nt, p1 = pre_t(o1, l1, jnp.asarray(toks), pt, lens, p1)
    lg, p2 = pre_l(o2, l2, jnp.asarray(toks), pt, lens, p2)
    assert lg.shape == (1, 64)
    assert int(np.argmax(np.asarray(lg), -1)[0]) == int(nt[0])
    for _ in range(3):
        nt, p1 = dec_t(o1, l1, nt, pt, lens, p1)
        tok_from_logits = jnp.argmax(lg, -1)
        lg, p2 = dec_l(o2, l2, tok_from_logits, pt, lens, p2)
        lens = lens + 1
        assert int(np.argmax(np.asarray(lg), -1)[0]) == int(nt[0])


def test_prefill_kernel_mode_matches_gather():
    """prefill_attention='kernel' routes chunk attention through the
    Pallas paged prefill kernel; the token stream must equal the
    gather path, fp and int8."""
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    for kv_dtype in (None, "int8"):
        mk = lambda pa: factory(model, page_size=PS, n_pool_pages=16,
                                chunked_prefill=PS,
                                kv_cache_dtype=kv_dtype,
                                prefill_attention=pa)
        o1, l1, p1, pre_g, dec_g, *_ = mk("gather")
        o2, l2, p2, pre_k, dec_k, *_ = mk("kernel")
        rng = np.random.default_rng(8)
        toks = np.zeros((2, 2 * PS), np.int64)
        toks[0, :11] = rng.integers(1, 64, 11)
        toks[1, :14] = rng.integers(1, 64, 14)
        lens = jnp.asarray([11, 14], jnp.int32)
        book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2,
                            head_dim=8)
        for i in range(2):
            book.allocate(i, 3 * PS)
        pt = jnp.asarray(np.stack([book.tables[0], book.tables[1]]),
                         jnp.int32)
        ng, p1 = pre_g(o1, l1, jnp.asarray(toks), pt, lens, p1)
        nk, p2 = pre_k(o2, l2, jnp.asarray(toks), pt, lens, p2)
        np.testing.assert_array_equal(np.asarray(ng), np.asarray(nk))
        cur = lens
        for _ in range(3):
            ng, p1 = dec_g(o1, l1, ng, pt, cur, p1)
            nk, p2 = dec_k(o2, l2, nk, pt, cur, p2)
            cur = cur + 1
            np.testing.assert_array_equal(np.asarray(ng), np.asarray(nk))


def test_prefix_cache_reuses_pages_and_skips_chunks():
    """vLLM-style prefix caching: a second request sharing a full-page
    prompt prefix acquires the cached pages (refcounted) and resumes
    prefill past them — tokens equal the uncached run exactly."""
    paddle.seed(9)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    o, l, pools, prefill, decode, *_ = factory(model, page_size=PS,
                                           n_pool_pages=16,
                                           chunked_prefill=PS)
    rng = np.random.default_rng(10)
    shared = rng.integers(1, 64, PS).tolist()        # one full page
    tailA = rng.integers(1, 64, 3).tolist()
    tailB = rng.integers(1, 64, 5).tolist()
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2, head_dim=8)

    def run(sid, prompt, resume):
        T = 2 * PS
        toks = np.zeros((1, T), np.int64)
        toks[0, :len(prompt)] = prompt
        book.allocate(sid, 3 * PS)
        pt = jnp.asarray([book.tables[sid]], jnp.int32)
        lens = jnp.asarray([len(prompt)], jnp.int32)
        book.lengths[sid] = len(prompt)
        nxt, p = prefill(o, l, jnp.asarray(toks), pt, lens,
                         pools_box[0], resume_from=resume)
        pools_box[0] = p
        out = [int(nxt[0])]
        cur = lens
        for _ in range(3):
            nxt, pools_box[0] = decode(o, l, nxt, pt, cur, pools_box[0])
            cur = cur + 1
            out.append(int(nxt[0]))
        return out

    pools_box = [pools]

    # request A: no cache; publish its prompt pages
    promptA = shared + tailA
    nc = book.acquire_prefix("A", promptA)
    assert nc == 0
    outA = run("A", promptA, resume=0)
    book.register_prefix("A", promptA)

    # request B: same first page — acquire + resume past it
    promptB = shared + tailB
    ncB = book.acquire_prefix("B", promptB)
    assert ncB == PS
    assert book.tables["B"][0] == book.tables["A"][0]  # SHARED page
    assert book._refs[book.tables["A"][0]] == 2
    outB = run("B", promptB, resume=ncB)

    # oracle: B uncached in a fresh book/pools
    o2, l2, pools2, prefill2, decode2, *_ = factory(model, page_size=PS,
                                                n_pool_pages=16,
                                                chunked_prefill=PS)
    book2 = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2,
                         head_dim=8)
    pools_box2 = [pools2]

    def run2(prompt):
        T = 2 * PS
        toks = np.zeros((1, T), np.int64)
        toks[0, :len(prompt)] = prompt
        book2.allocate("x", 3 * PS)
        pt = jnp.asarray([book2.tables["x"]], jnp.int32)
        lens = jnp.asarray([len(prompt)], jnp.int32)
        nxt, pools_box2[0] = prefill2(o2, l2, jnp.asarray(toks), pt,
                                      lens, pools_box2[0])
        out = [int(nxt[0])]
        cur = lens
        for _ in range(3):
            nxt, pools_box2[0] = decode2(o2, l2, nxt, pt, cur,
                                         pools_box2[0])
            cur = cur + 1
            out.append(int(nxt[0]))
        return out

    np.testing.assert_array_equal(outB, run2(promptB))

    # freeing A keeps the shared page alive for B; freeing B parks the
    # published page in the evictable LRU (retention) — it stays
    # matchable until allocation pressure reclaims it
    page = book.tables["A"][0]
    book.free("A")
    assert book._refs[page] == 1 and page not in book._free
    book.free("B")
    assert page in book._evictable and page not in book._free
    assert book.match_prefix(shared) == PS


def test_fixed_shape_batching_never_recompiles():
    """The serving property the paged design promises: one compiled
    decode executable serves every mix of live/pad slots — page tables
    and lengths are data, not shapes (pad slots: length 0, page 0)."""
    paddle.seed(11)
    cfg = LlamaConfig.tiny(vocab=32, hidden=32, layers=1, heads=2,
                           kv_heads=1)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.models.nlp.llama_decode import (
        llama_paged_decode_factory as factory)
    o, l, pools, prefill, decode, *_ = factory(model, page_size=PS,
                                           n_pool_pages=8)
    B, W = 2, 2
    toks = jnp.asarray(np.ones((B, PS), np.int64))
    pt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    lens = jnp.asarray([5, 3], jnp.int32)
    nxt, pools = prefill(o, l, toks, pt, lens, pools)
    mixes = [  # (page_tables, lengths, tokens) — shapes identical
        (pt, lens, nxt),
        (jnp.asarray([[1, 2], [0, 0]], jnp.int32),
         jnp.asarray([6, 0], jnp.int32), nxt),          # slot 1 empty
        (jnp.asarray([[5, 6], [3, 4]], jnp.int32),
         jnp.asarray([1, 7], jnp.int32), nxt),          # new request
    ]
    for ptx, lnx, tok in mixes:
        out, pools = decode(o, l, tok, ptx, lnx, pools)
        assert np.isfinite(np.asarray(out)).all() or True  # int tokens
    assert decode._cache_size() == 1, decode._cache_size()


def test_decode_n_matches_per_step_loop():
    """The factory's scan-amortized decode_n (n steps in ONE compiled
    program — the serving loop's dispatch amortizer) must emit exactly
    the per-step decode_step tokens and leave identical pools."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    outer, layers, pools, prefill, decode_step, decode_n = \
        llama_paged_decode_factory(model, page_size=PS, n_pool_pages=16)

    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 64, 5).tolist(),
               rng.integers(1, 64, 3).tolist()]
    lengths = np.asarray([len(p) for p in prompts], np.int32)
    toks = np.zeros((2, PS), np.int64)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2, head_dim=8)
    for i in range(2):
        book.allocate(i, 3 * PS)
    pt = jnp.asarray(np.stack([book.tables[0], book.tables[1]]),
                     jnp.int32)

    N = 5
    nxt, pools = prefill(outer, layers, jnp.asarray(toks), pt,
                         jnp.asarray(lengths), pools)

    # per-step reference (fresh pools for the scan run: deep-copy now)
    import jax
    pools_scan = jax.tree.map(jnp.copy, pools)
    ref_nxt, lens = nxt, jnp.asarray(lengths)
    ref = []
    pools_ref = pools
    for _ in range(N):
        ref_nxt, pools_ref = decode_step(outer, layers, ref_nxt, pt,
                                         lens, pools_ref)
        lens = lens + 1
        ref.append(np.asarray(ref_nxt))
    ref = np.stack(ref, 0)  # (N, B)

    emits, last, pools_scan = decode_n(outer, layers, nxt, pt,
                                       jnp.asarray(lengths), pools_scan,
                                       N)
    np.testing.assert_array_equal(np.asarray(emits), ref)
    np.testing.assert_array_equal(np.asarray(last), ref[-1])
    for a, b in zip(jax.tree.leaves(pools_scan),
                    jax.tree.leaves(pools_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


def test_decode_n_logits_mode_greedy_feedback():
    """decode_n with emit="logits": per-step logits stack to (N, B, V),
    the greedy-argmax feedback reproduces token-mode output, and an
    int64 seed token (np.argmax default) doesn't break the scan carry."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(2)
    prompt = rng.integers(1, 64, 6).tolist()
    lengths = jnp.asarray(np.asarray([len(prompt)], np.int32))
    toks = np.zeros((1, PS), np.int64)
    toks[0, :len(prompt)] = prompt

    def fresh_table():
        book = PagedKVCache(n_pages=16, page_size=PS, kv_heads=2,
                            head_dim=8)
        book.allocate(0, 3 * PS)
        return jnp.asarray(np.stack([book.tables[0]]), jnp.int32)

    # token mode reference
    outer, layers, pools, prefill, _, decode_n = \
        llama_paged_decode_factory(model, page_size=PS, n_pool_pages=16)
    pt = fresh_table()
    tok0, pools = prefill(outer, layers, jnp.asarray(toks), pt, lengths,
                          pools)
    tok0_np = np.asarray(tok0)
    emits_t, last_t, _ = decode_n(outer, layers, tok0, pt, lengths,
                                  pools, 4)

    # logits mode: caller-side greedy, int64 seed on purpose
    outer, layers, pools, prefill, _, decode_n = \
        llama_paged_decode_factory(model, page_size=PS, n_pool_pages=16,
                                   emit="logits")
    pt = fresh_table()
    logits0, pools = prefill(outer, layers, jnp.asarray(toks), pt,
                             lengths, pools)
    tok0_l = np.argmax(np.asarray(logits0), -1)
    assert tok0_l.dtype == np.int64
    np.testing.assert_array_equal(tok0_l.astype(np.int32), tok0_np)
    emits_l, last_l, _ = decode_n(outer, layers, jnp.asarray(tok0_l),
                                  pt, lengths, pools, 4)

    assert np.asarray(emits_l).shape == (4, 1, 64)
    np.testing.assert_array_equal(np.argmax(np.asarray(emits_l), -1),
                                  np.asarray(emits_t))
    np.testing.assert_array_equal(np.asarray(last_l),
                                  np.asarray(last_t))
