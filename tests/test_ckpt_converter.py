"""Checkpoint re-shard converter tests (the converter.py capability,
SURVEY.md §5): merge shards from one topology, re-slice to another, and the
jax NamedSharding bridge on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    Converter, dist_attr_from_sharding, load_distributed_checkpoint,
    merge_with_dist_attr, save_distributed_checkpoint, shards_from_array,
    slice_with_dist_attr,
)


def attr(process_shape, dims_mapping, group=None):
    n = int(np.prod(process_shape))
    return {"process_shape": list(process_shape),
            "process_group": group or list(range(n)),
            "dims_mapping": list(dims_mapping)}


class TestMergeSlice:
    def test_roundtrip_1d_split(self):
        full = np.arange(24, dtype=np.float32).reshape(6, 4)
        a = attr([2], [0, -1])
        shards = slice_with_dist_attr(full, a)
        assert shards[0].shape == (3, 4)
        np.testing.assert_array_equal(merge_with_dist_attr(shards, a), full)

    def test_roundtrip_2d_mesh(self):
        full = np.arange(64, dtype=np.float32).reshape(8, 8)
        a = attr([2, 2], [0, 1])
        shards = slice_with_dist_attr(full, a)
        assert len(shards) == 4 and shards[0].shape == (4, 4)
        np.testing.assert_array_equal(merge_with_dist_attr(shards, a), full)
        # row-major group order: shard 1 is mesh coords (0, 1) -> cols 4:8
        np.testing.assert_array_equal(shards[1], full[:4, 4:])

    def test_replicated_dim(self):
        full = np.random.rand(4, 6).astype(np.float32)
        a = attr([2], [-1, 0])
        shards = slice_with_dist_attr(full, a)
        assert shards[0].shape == (4, 3)
        np.testing.assert_array_equal(merge_with_dist_attr(shards, a), full)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            slice_with_dist_attr(np.zeros((5, 4)), attr([2], [0, -1]))


class TestConverter:
    def test_tp2_to_tp4(self):
        full = np.arange(32, dtype=np.float32).reshape(8, 4)
        pre = attr([2], [0, -1])
        cur = attr([4], [0, -1])
        shards2 = slice_with_dist_attr(full, pre)
        conv = Converter({"w": shards2}, {"w": pre}, {"w": cur})
        out = conv.convert()
        assert len(out["w"]) == 4
        np.testing.assert_array_equal(
            merge_with_dist_attr(out["w"], cur), full)

    def test_axis_change(self):
        full = np.arange(32, dtype=np.float32).reshape(8, 4)
        pre = attr([2], [0, -1])   # row split
        cur = attr([2], [-1, 0])   # col split
        out = Converter({"w": slice_with_dist_attr(full, pre)},
                        {"w": pre}, {"w": cur}).convert()
        np.testing.assert_array_equal(out["w"][0], full[:, :2])

    def test_gather_to_replicated(self):
        full = np.random.rand(4, 4).astype(np.float32)
        pre = attr([4], [0, -1])
        cur = attr([1], [-1, -1])
        out = Converter({"w": slice_with_dist_attr(full, pre)},
                        {"w": pre}, {"w": cur}).convert()
        np.testing.assert_array_equal(out["w"][0], full)

    def test_same_attr_passthrough(self):
        full = np.random.rand(4, 4).astype(np.float32)
        a = attr([2], [0, -1])
        shards = slice_with_dist_attr(full, a)
        out = Converter({"w": shards}, {"w": a}, {"w": a}).convert()
        np.testing.assert_array_equal(out["w"][0], shards[0])

    def test_prefix_match(self):
        full = np.random.rand(4, 4).astype(np.float32)
        a = attr([1], [-1, -1])
        conv = Converter({"layer0.weight": [full]},
                         {"layer0.weight": a},
                         {"layer0.weight.renamed": a})
        with pytest.raises(ValueError):
            conv.convert(strict=True)
        out = conv.convert(strict=False)
        np.testing.assert_array_equal(out["layer0.weight.renamed"][0], full)


class TestJaxBridge:
    def test_dist_attr_from_named_sharding(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "mp"))
        sh = NamedSharding(mesh, P("mp", None))
        a = dist_attr_from_sharding(sh, (8, 4))
        assert a["process_shape"] == [2, 4]
        assert a["dims_mapping"] == [1, -1]

    def test_shards_from_sharded_array(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = np.array(jax.devices()[:8]).reshape(8)
        mesh = Mesh(devs, ("mp",))
        full = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        sharded = jax.device_put(full, NamedSharding(mesh, P("mp", None)))
        shards = shards_from_array(sharded)
        assert len(shards) == 8 and shards[0].shape == (1, 4)
        np.testing.assert_array_equal(np.concatenate(shards), np.asarray(full))

    def test_save_load_distributed_roundtrip(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "mp"))
        w = jax.device_put(jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
                           NamedSharding(mesh, P(None, "mp")))
        b = jnp.ones((4,), jnp.float32)
        path = str(tmp_path / "dist.ckpt")
        save_distributed_checkpoint({"w": w, "b": b}, path)
        # load merged (topology-free)
        merged = load_distributed_checkpoint(path)
        np.testing.assert_array_equal(merged["w"], np.asarray(w))
        # load re-sharded to a 4-way row split
        cur = {"w": attr([4], [0, -1]), "b": attr([1], [-1])}
        out = load_distributed_checkpoint(path, cur)
        assert out["w"][0].shape == (1, 4)
        np.testing.assert_array_equal(
            merge_with_dist_attr(out["w"], cur["w"]), np.asarray(w))
