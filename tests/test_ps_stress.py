"""PS scale/concurrency stress: multi-PROCESS trainers, large tables.

~ the brpc PS many-workers contract (brpc_ps_server.cc one handler
thread per worker; table/memory_sparse_table.cc shard locking) and the
SSD table capacity story (table/ssd_sparse_table.cc). Thread-level
concurrency is covered in test_ps_server.py; here the workers are real
processes (separate interpreters, real sockets) and the SSD variant's
id space exceeds mem_rows so eviction happens mid-training.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.distributed.ps import PSServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, sys
    import numpy as np
    from paddle_tpu.distributed.ps import PSClient

    addr, rank, n_ids, rounds = (sys.argv[1], int(sys.argv[2]),
                                 int(sys.argv[3]), int(sys.argv[4]))
    c = PSClient(server_addr=addr)
    # disjoint id range per rank -> exact-once effect verifiable
    base = rank * n_ids
    ids = np.arange(base, base + n_ids, dtype=np.int64)
    for r in range(rounds):
        rows = c.pull_sparse(ids)
        c.push_sparse(ids, np.ones_like(rows))  # constant unit grad
    # geo-style async pushes on a SHARED range (contended across ranks)
    shared = np.arange(0, 64, dtype=np.int64) + 10_000_000
    rows = c.pull_sparse(shared)
    for r in range(rounds):
        c.async_push_sparse(shared, np.ones_like(rows))
    c.flush()
    c.close()
    print(json.dumps({"rank": rank, "ok": True}))
""")

N_WORKERS, N_IDS, ROUNDS, LR = 3, 2000, 5, 0.1


@pytest.fixture
def server():
    srv = PSServer(port=0)
    yield srv
    srv.stop()


def _spawn_workers(addr):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", WORKER, addr, str(rank), str(N_IDS),
         str(ROUNDS)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for rank in range(N_WORKERS)]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, (out[-400:], err[-400:])


def _check_rows(table):
    """SGD with unit grads: row = init - lr * n_pushes; init_std=0.01
    makes the expected shift dominate."""
    for rank in range(N_WORKERS):
        ids = np.arange(rank * N_IDS, (rank + 1) * N_IDS, dtype=np.int64)
        rows = table.pull(ids)
        np.testing.assert_allclose(rows, -LR * ROUNDS, atol=0.08)
    # shared contended range took every rank's async pushes exactly once
    shared = np.arange(0, 64, dtype=np.int64) + 10_000_000
    np.testing.assert_allclose(table.pull(shared),
                               -LR * ROUNDS * N_WORKERS, atol=0.08)


def test_memory_table_3proc(server):
    table = server.add_sparse_table(0, dim=8, lr=LR, init_std=0.01)
    _spawn_workers(f"127.0.0.1:{server.port}")
    assert table.size() == N_WORKERS * N_IDS + 64
    _check_rows(table)


def test_ssd_table_eviction_under_load(server, tmp_path):
    # mem_rows far below the touched id space: pushes/pulls force
    # eviction to sqlite mid-training; correctness must survive it
    table = server.add_ssd_sparse_table(
        0, dim=8, path=str(tmp_path / "ssd.db"), mem_rows=500,
        lr=LR, init_std=0.01)
    _spawn_workers(f"127.0.0.1:{server.port}")
    assert table.size() == N_WORKERS * N_IDS + 64
    assert len(table._rows) <= 500  # eviction actually happened
    _check_rows(table)
