"""Quantized paged KV serving: the int8 page tier.

The claims: ``kv_quant='int8'`` stores EVERY pool page as int8 data +
per-slot f32 scales (the serving spelling of kv_cache_dtype='int8'),
halving-or-better the per-device pool byte census at equal page count
— so one HBM budget holds more pages; ``kv_quant='pressure'`` keeps
hot pages full precision and compacts pages parked in the evictable
LRU to int8 instead of freeing them — triggered by a byte budget at
allocation time and by a ``pool_bytes_per_device`` ThresholdRule
incident delivered through ``QoSScheduler.note_incident`` (capacity
degradation one rung BEFORE any shedding tier), with every flip and
compaction batch deterministic on the virtual clock; the quantized
tier is an OVERLAY on the resident+evictable+free census (never a
fourth state, dies with a recycled page id — the wrong-context-KV
hazard); disaggregated handoffs carry the tier; ``kv_quant=None``
stays byte-identical to the pre-quant engine (outputs, reports,
registry); and the ``serving_quant`` bench-gate family passes its
pass rows and fails its FAIL rows.
"""
import dataclasses as dc
import json
import os
import sys
from collections import Counter

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.nlp.llama_decode import (
    compact_kv_pages, export_quant_pages, import_quant_pages,
    kv_quant_page_bytes, llama_serving_decode_factory)
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs.slo import ThresholdRule
from paddle_tpu.ops.pallas.paged_attention import PagedKVCache
from paddle_tpu.serving import (ClusterRouter, QoSScheduler, Request,
                                ServingEngine, make_sim_serving,
                                synthesize_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COSTS = {"prefill_unit": 1.0, "decode": 1.0}


def _sim_engine(kv_quant=None, slots=8, n_pool_pages=None, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", dict(COSTS))
    return ServingEngine(
        serving=make_sim_serving(
            max_len=64, page_size=8, slots=slots, vocab=509,
            n_pool_pages=(n_pool_pages if n_pool_pages is not None
                          else slots * 8 + 1 + 16),
            kv_quant=kv_quant),
        slots=slots, policy="paged", **kw)


def _churn_trace(seed=0, n=40):
    return synthesize_trace(
        seed=seed, n_requests=n, arrival="poisson",
        mean_interarrival=0.5, prompt_len=(4, 16), output_len=(8, 24),
        vocab_size=509, shared_prefix_frac=0.3, prefix_len=8,
        churn_frac=0.2, rid_prefix="m")


# --- bookkeeper: the quantized tier overlay -----------------------------


def test_note_kv_quant_validation():
    book = PagedKVCache(8, 4, 1, 8)
    assert book.stored_bytes() is None  # unpriced until armed
    with pytest.raises(ValueError, match="unknown mode"):
        book.note_kv_quant("fp4")
    book.note_kv_quant("int8", fp_bytes_per_page=100,
                       q_bytes_per_page=30)
    book.allocate("a", 8)  # 2 pages, every one priced int8
    assert book.stored_bytes() == 60


def test_mark_quantized_requires_occupied():
    book = PagedKVCache(8, 4, 1, 8)
    book.note_kv_quant("pressure", 100, 30)
    book.allocate("a", 4)
    p = book.tables["a"][0]
    book.mark_quantized([p])
    assert book.quantized_pages() == {p}
    with pytest.raises(ValueError, match="not occupied"):
        book.mark_quantized([7])  # a free page has no content to tier
    # quantized_pages is a snapshot, not the live set
    book.quantized_pages().add(99)
    assert 99 not in book.quantized_pages()


def test_stored_bytes_tier_pricing():
    """The dynamic pressure signal: occupied pages priced by tier,
    shrinking on compaction, zeroed when the page frees."""
    book = PagedKVCache(8, 4, 1, 8)
    book.note_kv_quant("pressure", fp_bytes_per_page=100,
                       q_bytes_per_page=30)
    book.allocate("a", 8)
    assert book.stored_bytes() == 200
    book.mark_quantized([book.tables["a"][0]])
    assert book.stored_bytes() == 130
    book.free("a")  # unpublished: pages free, the tier dies with them
    assert book.stored_bytes() == 0
    assert book.quantized_pages() == set()
    assert book.census_ok()


def test_compact_evictable_parks_not_forgets():
    """Compaction spends the evictable LRU oldest-first through the
    device callback, keeps keys live (the chains still match and
    revive), and the census never moves — nothing is forgotten."""
    ps = 4
    book = PagedKVCache(8, ps, 1, 8)
    calls = []
    book.note_kv_quant("pressure", 100, 30, compact_cb=calls.append)
    X = list(range(10, 10 + ps))
    Y = list(range(20, 20 + ps))
    book.acquire_prefix("a", X + Y)
    book.allocate("a", 2 * ps)
    book.register_prefix("a", X + Y)
    book.free("a")  # both published pages park in the LRU
    cands = book.compact_candidates()
    assert len(cands) == 2
    ids = book.compact_evictable(max_pages=1)
    assert ids == cands[:1] and calls == [ids]
    assert book.quantized_pages() == set(ids)
    assert book.compact_candidates() == cands[1:]  # never re-spent
    book.compact_evictable()
    assert book.quantized_pages() == set(cands)
    assert book.cache_stats()["compactions"] == 2
    assert book.census_ok()
    # keys stayed live: the chain revives WITH its tier intact
    assert book.match_prefix(X + Y) == 2 * ps
    assert book.acquire_prefix("b", X + Y) == 2 * ps
    assert book.quantized_pages() == set(cands)
    assert book.census_ok()


def test_allocate_compacts_under_byte_budget():
    """Byte-budget admission: compaction before refusal, and a
    genuine refusal mutates nothing."""
    ps = 4
    book = PagedKVCache(8, ps, 1, 8)
    book.note_kv_quant("pressure", fp_bytes_per_page=100,
                       q_bytes_per_page=20, byte_budget=320)
    X = list(range(10, 10 + ps))
    book.acquire_prefix("a", X)
    book.allocate("a", ps)
    book.register_prefix("a", X)
    book.free("a")  # one parked fp page: 100 stored bytes
    book.allocate("b", 2 * ps)  # projected 300 <= 320: no compaction
    assert book.quantized_pages() == set()
    book.allocate("c", ps)  # projected 400 > 320: compact the parked
    assert len(book.quantized_pages()) == 1
    assert book.stored_bytes() == 320
    assert book.census_ok()
    before = (list(book._free), dict(book._refs),
              set(book._quant), book.stored_bytes())
    with pytest.raises(MemoryError, match="byte budget"):
        book.allocate("d", ps)  # nothing left to compact
    assert (list(book._free), dict(book._refs),
            set(book._quant), book.stored_bytes()) == before


def test_eviction_recycling_clears_tier():
    """The wrong-context-KV regression, int8 edition: a recycled page
    id must never read stale int8 content or match stale chains."""
    ps = 4
    book = PagedKVCache(4, ps, 1, 8)  # 3 usable pages
    book.note_kv_quant("pressure", 100, 30)
    X = list(range(10, 10 + ps))
    book.acquire_prefix("a", X)
    book.allocate("a", ps)
    book.register_prefix("a", X)
    book.free("a")
    pX = next(iter(book._evictable))
    book.compact_evictable()
    assert pX in book.quantized_pages()
    book.allocate("b", 3 * ps)  # pressure: the parked page recycles
    assert pX in book.tables["b"]
    assert pX not in book.quantized_pages()
    assert book.match_prefix(X) == 0
    assert book.census_ok()


def test_purge_clears_both_tiers():
    book = PagedKVCache(8, 4, 1, 8)
    book.note_kv_quant("pressure", 100, 30)
    book.allocate("a", 8)
    book.mark_quantized(book.tables["a"])
    e0 = book.epoch
    book.purge()
    assert book.quantized_pages() == set()
    assert book.stored_bytes() == 0
    assert book.census_ok() and book.epoch == e0 + 1
    cs = book.cache_stats()
    assert cs["free_pages"] == 7 and cs["quantized_pages"] == 0


def test_cache_stats_quant_bucket_presence():
    """PR-5 presence convention at the census: the quantized bucket
    exists only when a tier is armed; always-int8 counts every
    occupied page."""
    plain = PagedKVCache(8, 4, 1, 8)
    plain.allocate("a", 8)
    cs = plain.cache_stats()
    for k in ("quantized_pages", "compactions", "stored_bytes"):
        assert k not in cs
    q = PagedKVCache(8, 4, 1, 8)
    q.note_kv_quant("int8", 100, 30)
    q.allocate("a", 8)
    cs = q.cache_stats()
    assert cs["quantized_pages"] == 2
    assert cs["stored_bytes"] == 60
    assert q.census_ok()


# --- scheduler pressure seam --------------------------------------------


class _Inc:
    severity = "warn"

    def __init__(self, signal="pool_bytes_per_device"):
        self.open = True
        self.evidence = {"signal": signal}


def test_scheduler_pressure_seam_unit():
    s = QoSScheduler()
    s.note_incident(_Inc())        # untracked: ignored
    assert not s.pressure_active()
    s.track_pressure = True
    s.note_incident(_Inc("queue_depth"))  # wrong signal: ignored
    assert not s.pressure_active()
    inc = _Inc()                   # warn severity qualifies: the
    s.note_incident(inc)           # compaction rung is low-regret
    assert s.pressure_active()
    inc.open = False
    assert not s.pressure_active()  # closed incidents prune lazily
    s.note_incident(_Inc())
    assert s.pressure_active()
    s.reset()                      # per-run monitors die with the run
    assert not s.pressure_active()


# --- sim engine: int8 mode, None identity, report block -----------------


def test_sim_int8_parity_bytes_and_result_block():
    trace = _churn_trace()
    e_fp = _sim_engine()
    e_q = _sim_engine(kv_quant="int8")
    r_fp = e_fp.run(trace)
    r_q = e_q.run(trace)
    # the sim's token-hash pools are lossless under any codec: exact
    # token parity is the sim-scale claim (the real factory's is the
    # teacher-forced logit bound in the bench)
    assert r_q.outputs == r_fp.outputs
    # unsharded + unquantized: no byte census at all (PR-10 shape)
    assert e_fp.pool_bytes_per_device() is None
    sim = e_q.serving
    assert e_q.pool_bytes_per_device() \
        == sim.page_bytes_[1] * sim.n_pool_pages_
    st = r_q.kv_quant_stats
    assert st["mode"] == "int8" and "stored_bytes" in st
    assert "flips" not in st  # pressure-only keys stay absent
    assert r_fp.kv_quant_stats is None
    rep = r_q.report()
    assert rep["kv_quant"] == "int8"
    assert rep["kv_quant_flips"] == 0 and rep["kv_compactions"] == 0
    assert rep["pool_bytes_per_device"] > 0
    assert r_q.cache_stats["invariant_ok"]
    assert r_q.cache_stats["quantized_pages"] >= 0


def test_kv_quant_none_byte_identity():
    """The identity clause: kv_quant=None is the pre-quant engine —
    outputs, slot logs, report keys, registry contents."""
    obs_metrics.REGISTRY.reset()
    trace = _churn_trace(seed=2, n=24)
    plain = _sim_engine().run(trace)
    again = _sim_engine(kv_quant=None).run(trace)
    assert again.outputs == plain.outputs
    assert again.slot_log == plain.slot_log
    assert again.kv_quant_stats is None
    rep = again.report()
    assert json.dumps(rep, sort_keys=True) \
        == json.dumps(plain.report(), sort_keys=True)
    for k in ("kv_quant", "kv_quant_flips", "kv_compactions",
              "kv_pages_compacted", "pool_bytes_per_device"):
        assert k not in rep
    names = {key[0] for key in obs_metrics.REGISTRY._metrics}
    assert not any(n.startswith(("serving_kv_compactions",
                                 "serving_kv_quant",
                                 "serving_pool_bytes"))
                   for n in names)


def test_pool_bytes_gauge_reports_actual_stored_bytes():
    """The PR-10 gauge regression: with a quantized tier the
    serving_pool_bytes_per_device gauge must price the pool as
    actually stored — static int8 arena bytes for always-int8, the
    moving stored-byte census for pressure — not the fp arena size."""
    obs_metrics.REGISTRY.reset()
    trace = _churn_trace(seed=3, n=24)
    e_q = _sim_engine(kv_quant="int8")
    e_q.run(trace)
    g = obs_metrics.REGISTRY.gauge(
        "serving_pool_bytes_per_device",
        "KV pool bytes resident on one device of the TP mesh")
    assert g.value == float(e_q.pool_bytes_per_device())
    res = _sim_engine(kv_quant="pressure").run(trace)
    # pressure streams the LOGICAL census: the gauge's final sample
    # is the run-end stored bytes, which the cache census also prices
    assert g.value == float(res.cache_stats["stored_bytes"])
    rep = res.report()
    assert rep["pool_bytes_per_device"] == int(g.value)


# --- pressure mode: incidents, flips, compaction, determinism -----------


def _pressure_engine(kv_quant="pressure", trace_sink=None):
    sim = make_sim_serving(max_len=64, page_size=8, n_pool_pages=48,
                           slots=8, vocab=509, chunked_prefill=8,
                           kv_quant=kv_quant)
    return ServingEngine(
        serving=sim, slots=8, policy="paged", clock="fixed",
        fixed_costs=dict(COSTS), scheduler=QoSScheduler(),
        trace=trace_sink,
        slo=([ThresholdRule(name="pool_pressure",
                            signal="pool_bytes_per_device",
                            bound=float(sim.page_bytes_[0] * 20),
                            op=">=", severity="page")]
             if kv_quant == "pressure" else None),
        kv_quant_budget=(sim.page_bytes_[0] * 40
                         if kv_quant == "pressure" else None))


def _pressure_trace():
    return synthesize_trace(seed=2, n_requests=80, vocab_size=509,
                            prompt_len=(8, 24), output_len=(4, 12),
                            shared_prefix_frac=0.3, prefix_len=16,
                            churn_frac=0.1)


def test_pressure_flips_and_compaction_deterministic():
    """The pressure tentpole at sim scale: the ThresholdRule incident
    flips the tier on (explain rule named), parked pages compact, the
    incident closes and the tier flips off — byte-identical across
    two seeded replays, token streams untouched vs plain."""
    from paddle_tpu import obs
    trace = _pressure_trace()
    tr = obs.Tracer()
    p1 = _pressure_engine(trace_sink=tr).run(trace)
    p2 = _pressure_engine().run(trace)
    pn = _pressure_engine(kv_quant=None).run(trace)
    qs = p1.kv_quant_stats
    assert qs["mode"] == "pressure"
    assert qs["pages_compacted"] > 0 and qs["compactions"] >= 1
    ons = [f for f in qs["flips"] if f["enabled"]]
    offs = [f for f in qs["flips"] if not f["enabled"]]
    assert ons and offs
    assert all("incident open" in f["rule"] for f in ons)
    assert all("closed" in f["rule"] for f in offs)
    assert p1.outputs == p2.outputs
    assert p1.kv_quant_stats == p2.kv_quant_stats
    assert p1.outputs == pn.outputs  # compaction is never shedding
    assert p1.cache_stats["invariant_ok"]
    assert any(i.rule == "pool_pressure" for i in p1.incidents)
    rep = p1.report()
    assert rep["kv_quant"] == "pressure"
    assert rep["kv_quant_flips"] == len(qs["flips"])
    assert rep["kv_pages_compacted"] == qs["pages_compacted"]
    names = {e.get("name") for e in tr.events}
    assert "kv_quant_flip" in names and "kv_compaction" in names


def test_pressure_trace_instants_absent_on_plain():
    from paddle_tpu import obs
    tr = obs.Tracer()
    _sim_engine(trace=tr).run(_churn_trace(seed=4, n=12))
    names = {e.get("name") for e in tr.events}
    assert "kv_quant_flip" not in names
    assert "kv_compaction" not in names


def test_pressure_counters_gated_on_config():
    obs_metrics.REGISTRY.reset()
    _sim_engine().run(_churn_trace(seed=5, n=12))
    names = {key[0] for key in obs_metrics.REGISTRY._metrics}
    assert not any(n.startswith(("serving_kv_compactions",
                                 "serving_kv_quant"))
                   for n in names)
    _pressure_engine().run(_pressure_trace())
    names = {key[0] for key in obs_metrics.REGISTRY._metrics}
    assert "serving_kv_compactions_total" in names
    assert "serving_kv_quant_flips_total" in names


def test_pressure_session_matches_run():
    """EngineSession's incremental drive produces the same streams
    and compaction evidence as run() (budget-driven compaction: no
    monitor needed, the allocate seam fires it)."""
    sim_kw = dict(max_len=64, page_size=8, n_pool_pages=30, slots=4,
                  vocab=509, kv_quant="pressure")

    def eng():
        sim = make_sim_serving(**sim_kw)
        return ServingEngine(serving=sim, slots=4, policy="paged",
                             clock="fixed", fixed_costs=dict(COSTS),
                             kv_quant_budget=sim.page_bytes_[0] * 22)

    trace = _churn_trace(seed=6, n=24)
    run_res = eng().run(trace)
    sess = eng().session()
    for r in sorted(trace, key=lambda r: (r.arrival, r.rid)):
        sess.advance_until(r.arrival)
        sess.submit(r)
    res = sess.finish()
    assert res.outputs == run_res.outputs
    assert res.kv_quant_stats == run_res.kv_quant_stats
    assert run_res.kv_quant_stats["compactions"] >= 1


# --- engine construction / validation -----------------------------------


def test_engine_kv_quant_validation():
    with pytest.raises(ValueError, match="kv_quant"):
        make_sim_serving(max_len=64, page_size=8, kv_quant="fp4")
    # a prebuilt factory's mode is authoritative: a conflicting
    # engine arg refuses instead of silently re-codec-ing the pool
    with pytest.raises(ValueError, match="conflicts"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, kv_quant="int8"),
            slots=4, policy="paged", kv_quant="pressure")
    with pytest.raises(ValueError, match="only means something"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, kv_quant="int8"),
            slots=4, policy="paged", kv_quant_budget=1 << 20)
    with pytest.raises(ValueError, match="> 0"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, kv_quant="pressure"),
            slots=4, policy="paged", kv_quant_budget=0)
    from paddle_tpu.models.nlp.llama_decode import SpecConfig
    with pytest.raises(ValueError, match="spec"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, spec_accept=0.5,
                                     kv_quant="pressure"),
            slots=4, policy="paged", spec=SpecConfig())
    with pytest.raises(ValueError, match="kv_quant='pressure'"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4, kv_quant="pressure"),
            slots=4, policy="dense")


def test_engine_kv_quant_validation_fp_conflict():
    with pytest.raises(ValueError, match="conflicts"):
        ServingEngine(
            serving=make_sim_serving(max_len=64, page_size=8,
                                     slots=4),
            slots=4, policy="paged", kv_quant="int8")


def test_prebuilt_factory_mode_adopted():
    eng = ServingEngine(
        serving=make_sim_serving(max_len=64, page_size=8, slots=4,
                                 kv_quant="int8"),
        slots=4, policy="paged")
    assert eng.kv_quant == "int8"
    # naming the matching mode explicitly is also fine
    eng2 = ServingEngine(
        serving=make_sim_serving(max_len=64, page_size=8, slots=4,
                                 kv_quant="int8"),
        slots=4, policy="paged", kv_quant="int8")
    assert eng2.kv_quant == "int8"


# --- disaggregated handoffs carry the tier ------------------------------


def _quant_cluster_engine(kv_quant):
    def spawn(name):
        return ServingEngine(
            serving=make_sim_serving(max_len=96, page_size=8,
                                     slots=8, vocab=101,
                                     kv_quant=kv_quant),
            slots=8, policy="paged", clock="fixed",
            fixed_costs=dict(COSTS), decode_chunk=4,
            prefill_chunk_budget=2)
    return spawn


def test_disagg_int8_handoffs_zero_failed():
    """A quantized chain moves prefill->decode exactly once: both
    stages on kv_quant='int8', zero FAILED handoffs, streams equal
    the lone int8 engine's."""
    trace = synthesize_trace(seed=0, n_requests=24, vocab_size=101,
                             prompt_len=(4, 16), output_len=(4, 10),
                             rid_prefix="h")
    res = ClusterRouter(_quant_cluster_engine("int8"), 2,
                        placement="disaggregated",
                        roles={"r0": "prefill", "r1": "decode"},
                        kv_transfer_unit=0.05).run(trace)
    cen = res.census()
    assert cen["conserved"] and cen["pool_census_ok"]
    assert cen["handoffs"]["failed"] == 0
    assert cen["handoffs"]["imported"] == len(trace)
    lone = ServingEngine(
        serving=make_sim_serving(max_len=96, page_size=8, slots=8,
                                 vocab=101, kv_quant="int8"),
        slots=8, policy="paged", clock="fixed",
        fixed_costs=dict(COSTS), decode_chunk=4).run(trace)
    outs = res.outputs()
    assert set(outs) == set(lone.outputs)
    assert all(outs[r] == lone.outputs[r] for r in outs)


def test_disagg_kv_quant_mismatch_filtered():
    """Placement filters on kv_quant like page_size/tp: an int8
    prefill worker's chains cannot land on an fp decode worker — the
    handoffs are recorded FAILED, never a tier-shape crash."""
    def spawn(name):
        return ServingEngine(
            serving=make_sim_serving(
                max_len=96, page_size=8, slots=8, vocab=101,
                kv_quant="int8" if name == "r0" else None),
            slots=8, policy="paged", clock="fixed",
            fixed_costs=dict(COSTS), decode_chunk=4,
            prefill_chunk_budget=2)
    trace = [Request(rid=f"g{i}", arrival=float(i),
                     prompt=tuple(range(1, 10)), max_new_tokens=4)
             for i in range(3)]
    res = ClusterRouter(spawn, 2, placement="disaggregated",
                        roles={"r0": "prefill", "r1": "decode"},
                        kv_transfer_unit=0.05).run(trace)
    cen = res.census()
    assert cen["conserved"]
    assert cen["handoffs"]["failed"] == len(trace)
    assert cen["handoffs"]["imported"] == 0


def test_import_refuses_kv_quant_mismatch():
    """The engine-level guard behind the placement filter: adopting a
    tier-shaped chain under a different kv_quant raises loudly."""
    src = ServingEngine(
        serving=make_sim_serving(max_len=96, page_size=8, slots=8,
                                 vocab=101, kv_quant="int8"),
        slots=8, policy="paged", clock="fixed",
        fixed_costs=dict(COSTS))
    sess = src.session(role="prefill")
    sess.submit(Request(rid="x", arrival=0.0,
                        prompt=tuple(range(1, 10)), max_new_tokens=4))
    sess.advance_until(1e6)
    assert sess.handoff_ready
    h = sess.handoff_ready[0]
    assert h.kv_quant == "int8"
    dst = ServingEngine(
        serving=make_sim_serving(max_len=96, page_size=8, slots=8,
                                 vocab=101),
        slots=8, policy="paged", clock="fixed",
        fixed_costs=dict(COSTS))
    dsess = dst.session(role="decode")
    dsess.submit_handoff(h)
    with pytest.raises(RuntimeError, match="kv_quant"):
        dsess.advance_until(1e6)


def test_import_mirrors_pressure_tier():
    """A pressure chain's tier positions ride the handoff as CHAIN
    indices and land in the importer's bookkeeper, so its byte census
    prices the adopted chain by its real tier."""
    def eng():
        return ServingEngine(
            serving=make_sim_serving(max_len=96, page_size=8,
                                     slots=8, vocab=101,
                                     kv_quant="pressure"),
            slots=8, policy="paged", clock="fixed",
            fixed_costs=dict(COSTS))
    sess = eng().session(role="prefill")
    sess.submit(Request(rid="x", arrival=0.0,
                        prompt=tuple(range(1, 18)), max_new_tokens=4))
    sess.advance_until(1e6)
    h = sess.handoff_ready[0]
    assert h.kv_quant == "pressure" and h.quant_pages == ()
    hq = dc.replace(h, quant_pages=(0,))  # as if page 0 was compacted
    dst = eng()
    dsess = dst.session(role="decode")
    dsess.submit_handoff(hq)
    dsess.advance_until(1.0)
    book = dsess.book
    assert book.tables["x"][0] in book.quantized_pages()
    assert book.census_ok()
    fp, q = dst.serving.page_bytes_
    occupied = len(book._refs) + len(book._evictable)
    assert book.stored_bytes() == (occupied - 1) * fp + q


# --- real tiny-llama factory --------------------------------------------


@pytest.fixture(scope="module")
def renv():
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return {"cfg": cfg, "model": model}


def _rfac(model, kv_quant=None, n_pages=None, tp=None, **kw):
    return llama_serving_decode_factory(
        model, max_len=64, page_size=8,
        n_pool_pages=(n_pages if n_pages is not None else 4 * 8 + 1 + 8),
        batch_capacity=4, chunked_prefill=8, kv_quant=kv_quant,
        tp=tp, **kw)


def _real_trace(seed=0, n=8):
    return synthesize_trace(seed=seed, n_requests=n,
                            arrival="poisson", mean_interarrival=0.5,
                            prompt_len=(4, 12), output_len=(4, 10),
                            vocab_size=97, churn_frac=0.2,
                            rid_prefix="q")


def test_real_factory_kv_quant_validation(renv):
    with pytest.raises(ValueError, match="kv_quant"):
        _rfac(renv["model"], kv_quant="fp4")
    with pytest.raises(ValueError, match="IS kv_cache_dtype"):
        _rfac(renv["model"], kv_quant="int8", kv_cache_dtype="bf16")
    with pytest.raises(ValueError, match="owns the pool codec"):
        _rfac(renv["model"], kv_quant="pressure",
              kv_cache_dtype="int8")
    with pytest.raises(ValueError, match="tp"):
        _rfac(renv["model"], kv_quant="pressure", tp=2)


def test_real_int8_is_the_serving_spelling(renv):
    """kv_quant='int8' IS kv_cache_dtype='int8' plus the serving
    surface: identical streams, plus the tier census/pricing the
    plain codec never grew — and the pool actually measures small."""
    import jax
    trace = _real_trace()
    e_q = ServingEngine(serving=_rfac(renv["model"], kv_quant="int8"),
                        slots=4, policy="paged", clock="fixed")
    e_d = ServingEngine(
        serving=_rfac(renv["model"], kv_cache_dtype="int8"),
        slots=4, policy="paged", clock="fixed")
    e_f = ServingEngine(serving=_rfac(renv["model"]), slots=4,
                        policy="paged", clock="fixed")
    r_q = e_q.run(trace)
    r_d = e_d.run(trace)
    e_f.run(trace)
    assert r_q.outputs == r_d.outputs
    assert r_q.kv_quant_stats["mode"] == "int8"
    assert r_d.kv_quant_stats is None  # the codec alone is not the tier
    bytes_q = e_q.pool_bytes_per_device()

    def pool_nbytes(e):
        return sum(int(a.nbytes) for a in
                   jax.tree_util.tree_leaves(e.serving._live_pools))
    assert bytes_q == pool_nbytes(e_q)
    assert bytes_q <= 0.55 * pool_nbytes(e_f)
    fp, q = e_q.serving.page_bytes_
    assert (fp, q) == kv_quant_page_bytes(renv["cfg"], 8, np.float32)
    assert r_q.cache_stats["invariant_ok"]


def test_real_pressure_parity_without_incident(renv):
    """Hot pages stay full precision: with no incident and no byte
    budget the pressure factory's streams are bit-equal to fp."""
    trace = _real_trace(seed=1)
    fp = ServingEngine(serving=_rfac(renv["model"]), slots=4,
                       policy="paged", clock="fixed").run(trace)
    pr = ServingEngine(
        serving=_rfac(renv["model"], kv_quant="pressure"),
        slots=4, policy="paged", clock="fixed").run(trace)
    assert pr.outputs == fp.outputs
    qs = pr.kv_quant_stats
    assert qs["mode"] == "pressure"
    assert qs["pages_compacted"] == 0 and qs["flips"] == []
    assert pr.cache_stats["invariant_ok"]


def test_real_pressure_compaction_churn_never_recompiles(renv):
    """Budget-driven compaction on the REAL dual-arena pool: parked
    pages compact at allocation time, every request still completes,
    the census holds — and compaction/churn adds ZERO compiles beyond
    the fp baseline (the (P,) tier mask is a jit input, so any
    compaction batch reuses the one compiled program)."""
    from paddle_tpu import obs
    trace = synthesize_trace(seed=3, n_requests=10,
                             arrival="poisson", mean_interarrival=0.5,
                             prompt_len=(8, 16), output_len=(4, 8),
                             vocab_size=97, shared_prefix_frac=0.5,
                             prefix_len=8, churn_frac=0.2,
                             rid_prefix="p")

    def compiles(kv_quant, budget_pages=None):
        srv = _rfac(renv["model"], kv_quant=kv_quant, n_pages=20)
        tr = obs.Tracer()
        eng = ServingEngine(
            serving=srv, slots=4, policy="paged", clock="fixed",
            trace=tr,
            kv_quant_budget=(srv.page_bytes_[0] * budget_pages
                             if budget_pages is not None else None))
        res = eng.run(trace)
        sites = Counter(e["args"]["site"] for e in tr.events
                        if e.get("name") == "jit.compile")
        return res, sites

    res_fp, sites_fp = compiles(None)
    res_pr, sites_pr = compiles("pressure", budget_pages=14)
    assert len(res_pr.outputs) == len(trace)
    assert res_pr.cache_stats["invariant_ok"]
    assert res_pr.kv_quant_stats["compactions"] >= 1
    assert res_pr.kv_quant_stats["quantized_pages"] >= 0
    assert sites_pr == sites_fp  # no extra compiles, ever


def test_real_tp_int8_parity(renv):
    """TP composes with the int8 tier: per-slot scales shard with the
    kv heads, streams bit-equal to the unsharded int8 engine."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    trace = _real_trace(seed=4, n=6)
    lone = ServingEngine(serving=_rfac(renv["model"],
                                       kv_quant="int8"),
                         slots=4, policy="paged",
                         clock="fixed").run(trace)
    tp = ServingEngine(serving=_rfac(renv["model"], kv_quant="int8",
                                     tp=2),
                       slots=4, policy="paged",
                       clock="fixed").run(trace)
    assert tp.outputs == lone.outputs
    assert tp.kv_quant_stats["mode"] == "int8"
    assert tp.cache_stats["invariant_ok"]


# --- factory-level codec units ------------------------------------------


def test_kv_quant_page_bytes_arithmetic():
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    fp, q = kv_quant_page_bytes(cfg, 8, np.float32)
    slots = 2 * 2 * 8  # layers * kv_heads * page_size
    assert fp == 2 * slots * 8 * 4   # k+v, head_dim f32
    assert q == 2 * slots * (8 + 4)  # int8 data + one f32 scale/slot
    assert q / fp == 0.375


def test_compact_kv_pages_codec_and_roundtrip():
    """The device half of compaction: masked pages land in the int8
    arena within the per-slot absmax error bound, unmasked arenas and
    the fp slots are untouched, and export/import re-materializes a
    mixed-tier chain exactly."""
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    L, H, P, S, D = 1, 2, 4, 4, 8
    kf = jnp.asarray(rng.normal(0, 1, (L, H, P, S, D)), jnp.float32)
    vf = jnp.asarray(rng.normal(0, 1, (L, H, P, S, D)), jnp.float32)
    zq = jnp.zeros((L, H, P, S, D), jnp.int8)
    zs = jnp.zeros((L, H, P, S), jnp.float32)
    tier = jnp.zeros((P,), bool)
    pools = ((kf, zq, zs), (vf, zq, zs), tier)
    mask = jnp.asarray([False, True, False, False])
    (kf2, kq2, ks2), (vf2, vq2, vs2), tier2 = compact_kv_pages(pools,
                                                               mask)
    assert list(np.asarray(tier2)) == [False, True, False, False]
    assert (np.asarray(kf2) == np.asarray(kf)).all()  # fp left dead
    assert not np.asarray(kq2)[:, :, 0].any()  # unmasked untouched
    deq = (np.asarray(kq2)[:, :, 1].astype(np.float32)
           * np.asarray(ks2)[:, :, 1][..., None])
    ref = np.asarray(kf)[:, :, 1]
    # per-slot absmax int8: error <= scale/2 = absmax/254
    bound = np.abs(ref).max(axis=-1, keepdims=True) / 127.0
    assert (np.abs(deq - ref) <= bound).all()
    pools2 = ((kf2, kq2, ks2), (vf2, vq2, vs2), tier2)
    data = export_quant_pages(pools2, [1, 2])
    fresh = ((jnp.zeros_like(kf), zq, zs),
             (jnp.zeros_like(vf), zq, zs),
             jnp.zeros((P,), bool))
    (kf3, kq3, ks3), _, tier3 = import_quant_pages(fresh, [0, 3],
                                                   data)
    assert list(np.asarray(tier3)) == [True, False, False, False]
    assert (np.asarray(kq3)[:, :, 0] == np.asarray(kq2)[:, :, 1]).all()
    assert (np.asarray(kf3)[:, :, 3] == np.asarray(kf)[:, :, 2]).all()


# --- trace_report + gate ------------------------------------------------


def test_trace_report_kv_quant_rows():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from trace_report import kv_quant_summary, report

    from paddle_tpu import obs
    tr = obs.Tracer()
    _pressure_engine(trace_sink=tr).run(_pressure_trace())
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t.json")
        tr.export(p)
        with open(p) as f:
            evts = json.load(f)["traceEvents"]
    row = kv_quant_summary(evts)
    assert row["bench"] == "trace_report_kv_quant"
    assert row["flips"] >= 2 and row["pages_compacted"] > 0
    assert row["flip_timeline"]
    txt = report(evts)
    assert "quantized KV tier" in txt

    tr2 = obs.Tracer()
    _sim_engine(trace=tr2).run(_churn_trace(seed=7, n=12))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t.json")
        tr2.export(p)
        with open(p) as f:
            evts2 = json.load(f)["traceEvents"]
    assert kv_quant_summary(evts2) is None
    assert "quantized KV tier" not in report(evts2)


def _gate_rows(bytes_ratio=0.32, tps=1.4, err=0.01, none_id=True,
               fp_refused=True, served=True, census=True,
               deterministic=True, parity=True, pages=80,
               fp_keys=False, drop_arm=None, drop_bench=None):
    rows = [
        {"bench": "serving_quant", "arm": "fp", "device": "cpu",
         "census_ok": census,
         **({"kv_quant": "int8"} if fp_keys else {})},
        {"bench": "serving_quant", "arm": "int8", "device": "cpu",
         "census_ok": census, "kv_quant": "int8"},
        {"bench": "serving_quant", "arm": "fp_fixed_bytes",
         "device": "cpu", "census_ok": census},
        {"bench": "serving_quant", "arm": "int8_fixed_bytes",
         "device": "cpu", "census_ok": census, "kv_quant": "int8"},
        {"bench": "serving_quant_pressure", "device": "sim",
         "deterministic": deterministic,
         "token_parity_vs_plain": parity,
         "pages_compacted": pages, "census_ok": census},
        {"bench": "serving_quant_summary", "device": "cpu",
         "bytes_ratio": bytes_ratio, "capacity_gain": 3.2,
         "tps_ratio_fixed_bytes": tps, "logit_rel_err": err,
         "none_identity": none_id, "capacity_fp_refused": fp_refused,
         "capacity_int8_served": served,
         "pressure_pages_compacted": pages, "census_ok": census}]
    if drop_arm:
        rows = [r for r in rows if r.get("arm") != drop_arm]
    if drop_bench:
        rows = [r for r in rows if r.get("bench") != drop_bench]
    return rows


def test_gate_serving_quant_pass_and_fails(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from bench_gate import check_serving_quant

    assert check_serving_quant(_gate_rows()) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["gate"] == "pass"
    assert out["bytes_ratio"] == 0.32

    for rows, frag in (
            (_gate_rows(bytes_ratio=0.8), "not actually smaller"),
            (_gate_rows(tps=0.7), "not converting to throughput"),
            (_gate_rows(err=0.2), "not faithful"),
            (_gate_rows(none_id=False), "must stay byte-identical"),
            (_gate_rows(fp_refused=False), "capacity pair"),
            (_gate_rows(served=False), "capacity pair"),
            (_gate_rows(census=False), "census"),
            (_gate_rows(deterministic=False), "pressure arm broken"),
            (_gate_rows(parity=False), "pressure arm broken"),
            (_gate_rows(pages=0), "pressure arm broken"),
            (_gate_rows(fp_keys=True), "no longer inert"),
            (_gate_rows(drop_arm="int8"), "missing arms"),
            (_gate_rows(drop_bench="serving_quant_pressure"),
             "UNVERIFIED"),
            (_gate_rows(drop_bench="serving_quant_summary"),
             "UNVERIFIED")):
        assert check_serving_quant(rows) == 1
        out = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert out["gate"] == "FAIL"
        assert frag in out["reason"]


@pytest.mark.slow
def test_quant_bench_arm_end_to_end(capsys):
    """The --kv-quant arm end to end: rows parse, the gate passes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_workload_bench as swb
    from bench_gate import check_serving_quant
    rc = swb.main(["--cpu", "--kv-quant", "--requests", "8"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    arms = {r.get("arm") for r in rows
            if r.get("bench") == "serving_quant"}
    assert {"fp", "int8", "fp_fixed_bytes",
            "int8_fixed_bytes"} <= arms
    assert check_serving_quant(rows) == 0
