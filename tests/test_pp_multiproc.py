"""Multi-process eager pipeline parallelism parity.

~ reference test strategy for PP (unittests launched via the launcher,
SURVEY.md §4): 2 stage processes, each building only ITS PipelineLayer
segment, exchanging activations/grads over TCPStore p2p in 1F1B order —
loss trajectory must match the single-process full-model run exactly.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys
import textwrap

import numpy as np

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    sys.path.insert(0, "/root/repo")
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
        pp_layers as PPL)
    LayerDesc, PipelineLayer = PPL.LayerDesc, PPL.PipelineLayer

    world = int(os.environ.get("PADDLE_WORLD_SIZE", "1"))
    rank = int(os.environ.get("PADDLE_GLOBAL_RANK", "0"))

    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": world}
    strategy.pipeline_configs = {"micro_batch_size": 4,
                                 "accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)

    def loss_fn(out, label):
        return paddle.nn.functional.mse_loss(out, label)

    paddle.seed(123)  # same init everywhere; each rank keeps its segment
    descs = [LayerDesc(nn.Linear, 16, 32),
             LayerDesc(nn.Tanh),
             LayerDesc(nn.Linear, 32, 32),
             LayerDesc(nn.Tanh),
             LayerDesc(nn.Linear, 32, 4)]
    model = PipelineLayer(descs, num_stages=world, loss_fn=loss_fn)
    model = fleet.distributed_model(model)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)

    rng = np.random.default_rng(5)
    x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    losses = []
    for step in range(4):
        if world > 1:
            loss = model.train_batch((x, y), opt)
        else:
            # single-process oracle: same micro-batching, full stack
            n = 2
            total = 0.0
            for i in range(n):
                xm = x[i * 4:(i + 1) * 4]
                ym = y[i * 4:(i + 1) * 4]
                out = model.forward_full(xm)
                l = loss_fn(out, ym) * (1.0 / n)
                l.backward()
                total += float(l.numpy()) * n
            opt.step()
            opt.clear_grad()
            loss = total / n
        losses.append(float(loss if isinstance(loss, float)
                            else loss.numpy()))

    out_dir = os.environ["TEST_OUT_DIR"]
    with open(os.path.join(out_dir, f"pp_rank{rank}.json"), "w") as f:
        json.dump(losses, f)
""")


def _run(tmp_path, nproc):
    script = tmp_path / "pp_trainer.py"
    script.write_text(TRAINER)
    out = tmp_path / f"np{nproc}"
    out.mkdir()
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(out)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_GLOBAL_RANK", None)
    env.pop("PADDLE_WORLD_SIZE", None)
    if nproc == 1:
        proc = subprocess.run([sys.executable, str(script)],
                              cwd="/root/repo", env=env, capture_output=True,
                              text=True, timeout=240)
    else:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", str(nproc), str(script)],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=240)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    losses = {}
    for r in range(nproc):
        p = out / f"pp_rank{r}.json"
        assert p.exists(), f"rank {r} wrote nothing: {proc.stdout}\n{proc.stderr}"
        losses[r] = json.loads(p.read_text())
    return losses


@pytest.mark.dist_retry(n=1)
def test_pp_two_stage_loss_parity(tmp_path):
    single = np.asarray(_run(tmp_path, 1)[0])
    multi = _run(tmp_path, 2)
    # every stage reports the broadcast final loss; both must equal the
    # single-process oracle per step
    np.testing.assert_allclose(np.asarray(multi[0]), single, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(multi[1]), single, rtol=1e-5,
                               atol=1e-6)
    assert single[-1] < single[0]
