"""Coverage for wrapper selection, norm variants, flags, amp O2, rng
tracker, ring-attention grads, MoE top-2, generated-op infermeta."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


class TestFleetWrapperSelection:
    def test_single_process_returns_model(self):
        from paddle_tpu.distributed import fleet
        fleet.init(is_collective=True)
        net = nn.Linear(2, 2)
        wrapped = fleet.distributed_model(net)
        # world==1 -> returned unwrapped (or DataParallel w/ nranks 1)
        out = wrapped(paddle.ones([1, 2])) if callable(wrapped) else None
        assert out.shape == [1, 2]

    def test_hybrid_optimizer_wraps(self):
        from paddle_tpu.distributed import (CommunicateTopology,
                                            HybridCommunicateGroup,
                                            set_hybrid_communicate_group)
        from paddle_tpu.distributed.fleet.meta_parallel import (
            HybridParallelOptimizer)
        topo = CommunicateTopology(["data", "pipe", "sharding", "sep",
                                    "model"], [1, 1, 1, 1, 8])
        hcg = HybridCommunicateGroup(topo)
        p = paddle.core_parameter if False else None
        from paddle_tpu.core.tensor import Parameter
        w = Parameter(np.ones(4, np.float32))
        inner = optimizer.SGD(0.1, parameters=[w],
                              grad_clip=nn.ClipGradByGlobalNorm(1.0))
        hp = HybridParallelOptimizer(inner, hcg, None)
        w._grad = paddle.to_tensor(np.full(4, 10.0, np.float32))
        hp.step()
        # clipped to global norm 1: grad = 10/20 each -> p = 1 - 0.1*0.5
        np.testing.assert_allclose(w.numpy(), 1 - 0.1 * 0.5, rtol=1e-5)
        set_hybrid_communicate_group(None)


class TestNormVariants:
    def test_sync_batchnorm_convert(self):
        net = nn.Sequential(nn.Conv2D(3, 8, 3), nn.BatchNorm2D(8))
        converted = nn.SyncBatchNorm.convert_sync_batchnorm(net)
        assert isinstance(converted[1], nn.SyncBatchNorm)
        out = converted(paddle.randn([2, 3, 8, 8]))
        assert out.shape[1] == 8

    def test_spectral_norm(self):
        sn = nn.SpectralNorm([4, 4], power_iters=5)
        w = paddle.randn([4, 4])
        out = sn(w)
        # spectral norm of output approx 1
        s = np.linalg.svd(out.numpy(), compute_uv=False)[0]
        assert abs(s - 1.0) < 0.2


class TestFlagsAndDebug:
    def test_check_nan_inf_flag(self):
        paddle.set_flags({"check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            with pytest.raises(FloatingPointError):
                paddle.log(x * 0.0 - 1.0)  # log(-1) = nan
        finally:
            paddle.set_flags({"check_nan_inf": False})

    def test_get_flags(self):
        flags = paddle.get_flags(["check_nan_inf"])
        assert flags["check_nan_inf"] is False


class TestAmpO2:
    def test_decorate_casts_model(self):
        net = nn.Linear(4, 4)
        paddle.amp.decorate(net, level="O2", dtype="bfloat16")
        assert net.weight.dtype == paddle.bfloat16

    def test_o2_autocast_covers_unlisted(self):
        a = paddle.randn([4])
        with paddle.amp.auto_cast(level="O2"):
            out = paddle.add(a, a)
        assert out.dtype == paddle.bfloat16


class TestRNGTracker:
    def test_rng_state_contexts(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            get_rng_state_tracker)
        tracker = get_rng_state_tracker()
        tracker.reset()
        tracker.add("mp_rng", 1234)
        with tracker.rng_state("mp_rng"):
            a = paddle.randn([4])
        with tracker.rng_state("mp_rng"):
            # different offset now — different draw
            b = paddle.randn([4])
        assert not np.allclose(a.numpy(), b.numpy())
        # outside the context the global generator is unaffected
        paddle.seed(7)
        c = paddle.randn([4])
        paddle.seed(7)
        d = paddle.randn([4])
        np.testing.assert_allclose(c.numpy(), d.numpy())


class TestRingAttentionGrad:
    def test_grad_matches_reference(self):
        from jax.sharding import Mesh
        from paddle_tpu.parallel import ring_attention
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("sep",))
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 2, 32, 8), np.float32))

        def loss_ring(q):
            return jnp.sum(ring_attention(q, q, q, mesh, causal=True) ** 2)

        def loss_ref(q):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(8)
            s = jnp.where(jnp.tril(jnp.ones((32, 32), bool)), s, -1e30)
            out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), q)
            return jnp.sum(out ** 2)

        gr = jax.grad(loss_ring)(q)
        gf = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-3, atol=2e-3)


class TestMoEGating:
    def test_top2_combines_two_experts(self):
        from paddle_tpu.incubate.distributed.models.moe import top2_gating
        logits = jnp.asarray(np.random.randn(16, 4).astype(np.float32))
        dispatch, combine, aux = top2_gating(logits, capacity=16)
        # most tokens should hit 2 slots
        per_token = np.asarray(dispatch.sum(axis=(1, 2)))
        assert per_token.max() <= 2 + 1e-6
        assert (per_token >= 1).all()
        # combine weights sum to ~1 for tokens with both slots kept
        cw = np.asarray(combine.sum(axis=(1, 2)))
        assert cw.max() <= 1 + 1e-5

    def test_expert_choice_exact_load(self):
        """Expert-choice routing: every expert takes EXACTLY capacity
        tokens (perfect balance by construction), no aux loss."""
        from paddle_tpu.incubate.distributed.models.moe import \
            expert_choice_gating
        logits = jnp.asarray(np.random.default_rng(0).normal(
            0, 1, (32, 4)).astype(np.float32))
        dispatch, combine, aux = expert_choice_gating(logits, capacity=8)
        per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
        np.testing.assert_allclose(per_expert, 8.0)  # exact
        assert float(aux) == 0.0
        # each (expert, slot) holds exactly one token
        np.testing.assert_allclose(np.asarray(dispatch.sum(0)), 1.0)
        # combine weights are the picked tokens' softmax probs
        assert np.asarray(combine).max() <= 1.0 + 1e-6

    def test_expert_choice_layer_runs_and_learns(self):
        from paddle_tpu.incubate.distributed.models.moe import MoELayer
        paddle.seed(3)
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                         gate="expert_choice")
        opt = paddle.optimizer.Adam(parameters=layer.parameters(),
                                    learning_rate=1e-2)
        x = paddle.to_tensor(np.random.default_rng(1).normal(
            0, 1, (2, 8, 16)).astype(np.float32))
        first = None
        for _ in range(6):
            out = layer(x)
            assert layer.aux_loss is not None
            loss = ((out - x) ** 2).mean()
            if first is None:
                first = float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first, (first, float(loss))


class TestGeneratedOps:
    def test_infer_meta_matches_run(self):
        from paddle_tpu import ops
        x = np.random.rand(6).astype(np.float32) + 0.5
        meta = ops.infer_meta("xlogy", jax.ShapeDtypeStruct((6,), np.float32),
                              jax.ShapeDtypeStruct((6,), np.float32))
        out = ops.xlogy(paddle.to_tensor(x), paddle.to_tensor(x))
        assert tuple(out.shape) == meta.shape
        np.testing.assert_allclose(out.numpy(), x * np.log(x), rtol=1e-5)

    def test_generated_grad(self):
        from paddle_tpu import ops
        x = paddle.to_tensor(np.array([0.5, 1.5], np.float32),
                             stop_gradient=False)
        out = ops.sinc(x)
        out.sum().backward()
        assert x.grad is not None


class TestProfilerExport:
    def test_spans_and_chrome_export(self, tmp_path):
        import json
        from paddle_tpu import profiler
        prof = profiler.Profiler(timer_only=True)
        prof.start()
        from paddle_tpu.profiler import _spans
        _spans.enabled = True
        _ = paddle.matmul(paddle.ones([8, 8]), paddle.ones([8, 8]))
        _spans.enabled = False
        prof.step(num_samples=8)
        p = str(tmp_path / "trace.json")
        prof.export(p)
        data = json.load(open(p))
        names = [e["name"] for e in data["traceEvents"]]
        assert any("matmul" in n for n in names)
        assert "avg step" in prof.step_info()
        prof.stop()


class TestAmpO2MasterWeights:
    def test_decorate_o2_enables_multi_precision(self):
        import jax.numpy as jnp
        from paddle_tpu import nn
        m = nn.Linear(8, 8)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        m2, o2 = paddle.amp.decorate(m, opt, level="O2")
        assert o2._multi_precision
        x = paddle.to_tensor(np.random.rand(2, 8).astype(np.float32))
        loss = paddle.sum(m2(x))
        loss.backward()
        o2.step()
        masters = [a["_master"] for a in o2._accumulators.values()
                   if "_master" in a]
        assert masters and all(mm.dtype == jnp.float32 for mm in masters)

    def test_decorate_o2_master_weight_false_opts_out(self):
        from paddle_tpu import nn
        m = nn.Linear(4, 4)
        o = paddle.optimizer.SGD(parameters=m.parameters())
        paddle.amp.decorate(m, o, level="O2", master_weight=False)
        assert not o._multi_precision
