"""Parity: vision/detection_jit (pure-jnp, jit-compiled) vs the host
numpy oracles in vision/detection — plus the end-to-end jitted SSD
train step (VERDICT r3 item 4: the ops the reference runs as CUDA
kernels must compile into the train step)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.vision import detection as D
from paddle_tpu.vision import detection_jit as J


def _rand_boxes(rng, n, lo=0.0, hi=60.0):
    xy = rng.uniform(lo, hi, (n, 2)).astype(np.float32)
    wh = rng.uniform(1.0, 20.0, (n, 2)).astype(np.float32)
    return np.concatenate([xy, xy + wh], -1)


def test_iou_clip_coder_parity():
    rng = np.random.default_rng(0)
    a, b = _rand_boxes(rng, 7), _rand_boxes(rng, 11)
    for normalized in (True, False):
        got = jax.jit(lambda x, y: J.iou_matrix(x, y, normalized))(a, b)
        want = D.iou_similarity(a, b, box_normalized=normalized).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    info = np.array([48.0, 64.0, 1.0], np.float32)
    got = jax.jit(J.clip_boxes)(a, info)
    np.testing.assert_allclose(np.asarray(got),
                               D.box_clip(a, info).numpy(), rtol=1e-6)

    pv = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    enc = jax.jit(J.encode_center_size)(b, pv, a)
    want = D.box_coder(b, pv, a, "encode_center_size").numpy()
    np.testing.assert_allclose(np.asarray(enc), want, rtol=1e-4,
                               atol=1e-5)
    # decode roundtrip, broadcast both ways
    deltas = rng.normal(0, 0.3, (7, 11, 4)).astype(np.float32)
    for axis in (0, 1):
        pr = b if axis == 0 else a
        got = jax.jit(lambda p, t: J.decode_center_size(
            p, pv, t, axis=axis))(pr, deltas)
        want = D.box_coder(pr, pv, deltas, "decode_center_size",
                           axis=axis).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-4)


def test_grid_parity():
    fm = np.zeros((1, 8, 3, 5), np.float32)
    img = np.zeros((1, 3, 48, 80), np.float32)

    got = J.anchor_grid(3, 5, [32.0, 64.0], [0.5, 1.0, 2.0], [16.0, 16.0])
    want, _ = D.anchor_generator(fm, [32.0, 64.0], [0.5, 1.0, 2.0],
                                 stride=[16.0, 16.0])
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-6)

    got = J.prior_box_grid(3, 5, 48, 80, [8.0, 16.0], [20.0, 40.0],
                           aspect_ratios=[2.0], flip=True)
    want, _ = D.prior_box(fm, img, [8.0, 16.0], [20.0, 40.0],
                          aspect_ratios=[2.0], flip=True)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-5)

    got = J.density_prior_box_grid(3, 5, 48, 80, [2, 1], [4.0, 8.0],
                                   fixed_ratios=[1.0, 2.0])
    want, _ = D.density_prior_box(fm, img, [2, 1], [4.0, 8.0],
                                  fixed_ratios=[1.0, 2.0])
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-5)


@pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
def test_match_priors_parity(match_type):
    rng = np.random.default_rng(1)
    for trial in range(4):
        G, P = rng.integers(1, 6), rng.integers(4, 24)
        iou = rng.uniform(0, 1, (G, P)).astype(np.float32)
        midx, mdist = jax.jit(
            lambda x: J.match_priors(x, None, match_type, 0.5))(iou)
        want_idx, want_dist = D.bipartite_match(iou, match_type, 0.5)
        np.testing.assert_array_equal(np.asarray(midx),
                                      want_idx.numpy())
        np.testing.assert_allclose(np.asarray(mdist),
                                   want_dist.numpy(), rtol=1e-6)


def test_match_priors_gt_mask():
    # padded gt rows (mask False) must never match
    iou = np.full((3, 6), 0.9, np.float32)
    mask = np.array([True, False, False])
    midx, _ = J.match_priors(iou, mask, "per_prediction", 0.5)
    assert set(np.asarray(midx).tolist()) <= {-1, 0}
    assert (np.asarray(midx) == 0).sum() >= 1


def test_ssd_loss_jit_matches_host():
    rng = np.random.default_rng(2)
    P, C, G = 16, 3, 2
    priors = _rand_boxes(rng, P, 0, 30) / 32.0
    gt = _rand_boxes(rng, G, 0, 30) / 32.0
    gtl = np.array([1, 2], np.int64)
    loc = rng.normal(0, 0.1, (P, 4)).astype(np.float32)
    conf = rng.normal(0, 0.1, (P, C)).astype(np.float32)

    want = float(D.ssd_loss(loc, conf, gt, gtl, priors))
    got = float(jax.jit(J.ssd_loss_jit)(
        loc, conf, gt, gtl, np.ones(G, bool), priors))
    assert abs(got - want) < 1e-4 * max(1.0, abs(want)), (got, want)

    # padding invariance: adding masked gt rows must not change the loss
    gt_pad = np.concatenate([gt, np.zeros((3, 4), np.float32)])
    gtl_pad = np.concatenate([gtl, np.zeros(3, np.int64)])
    mask = np.array([True, True, False, False, False])
    got_pad = float(jax.jit(J.ssd_loss_jit)(
        loc, conf, gt_pad, gtl_pad, mask, priors))
    assert abs(got_pad - got) < 1e-5


def test_generate_proposals_jit_parity():
    rng = np.random.default_rng(3)
    A, H, W = 3, 5, 6
    anchors, var = D.anchor_generator(
        np.zeros((1, 8, H, W), np.float32), [16.0, 32.0, 64.0], [1.0],
        stride=[8.0, 8.0])
    scores = rng.uniform(0, 1, (1, A, H, W)).astype(np.float32)
    deltas = rng.normal(0, 0.2, (1, 4 * A, H, W)).astype(np.float32)
    info = np.array([[40.0, 48.0, 1.0]], np.float32)

    want_rois, want_cnt = D.generate_proposals(
        scores, deltas, info, anchors, var, pre_nms_top_n=50,
        post_nms_top_n=10, nms_thresh=0.6, min_size=2.0)
    got_rois, got_sc, got_cnt = jax.jit(
        lambda s, d, i, an, v: J.generate_proposals_jit(
            s, d, i, an, v, pre_nms_top_n=50, post_nms_top_n=10,
            nms_thresh=0.6, min_size=2.0))(
        scores[0], deltas[0], info[0], anchors.numpy(), var.numpy())
    assert int(got_cnt) == int(want_cnt.numpy()[0])
    np.testing.assert_allclose(np.asarray(got_rois),
                               want_rois.numpy()[0], rtol=1e-4,
                               atol=1e-3)


def test_fpn_distribute_collect_parity():
    rng = np.random.default_rng(4)
    R = 12
    rois = _rand_boxes(rng, R, 0, 200)
    outs, restore = D.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    j_outs, j_counts, j_restore = jax.jit(
        lambda r: J.distribute_fpn_proposals_jit(
            r, jnp.ones(R, bool), 2, 5, 4, 224))(rois)
    j_outs = np.asarray(j_outs)
    j_counts = np.asarray(j_counts)
    for i, o in enumerate(outs):
        o = o.numpy().reshape(-1, 4)
        assert j_counts[i] == len(o)
        np.testing.assert_allclose(j_outs[i, :len(o)], o, rtol=1e-6)
    # restore_row round-trip: gathering the concatenated layout by
    # restore_row reproduces the input rois
    concat = j_outs.reshape(-1, 4)
    np.testing.assert_allclose(concat[np.asarray(j_restore)], rois,
                               rtol=1e-6)

    # collect: global top-n by score across levels
    L = 3
    mr = [_rand_boxes(rng, 5) for _ in range(L)]
    msc = [rng.uniform(0, 1, 5).astype(np.float32) for _ in range(L)]
    want_r, want_s = D.collect_fpn_proposals(mr, msc, 7)
    got_r, got_s, got_n = jax.jit(
        lambda r, s: J.collect_fpn_proposals_jit(
            r, s, jnp.ones((L, 5), bool), 7))(np.stack(mr),
                                              np.stack(msc))
    assert int(got_n) == 7
    np.testing.assert_allclose(np.asarray(got_s), want_s.numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_r), want_r.numpy(),
                               rtol=1e-6)


def test_jitted_ssd_train_step_end_to_end():
    """The VERDICT item-4 'done' check: one jax.jit train step covering
    anchor grid -> head forward -> matching -> multibox loss -> adam,
    loss decreasing, no host sync inside the step."""
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    paddle.seed(0)
    from paddle_tpu import nn

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(3, 8, 3, stride=4, padding=1)
            self.loc = nn.Conv2D(8, 4, 1)
            self.conf = nn.Conv2D(8, 2, 1)

        def forward(self, x):
            f = nn.functional.relu(self.conv(x))
            loc = self.loc(f).transpose([0, 2, 3, 1]).reshape([-1, 4])
            conf = self.conf(f).transpose([0, 2, 3, 1]).reshape([-1, 2])
            return loc, conf

    head = Head()
    params = {k: v._value for k, v in head.state_dict().items()}
    priors = J.anchor_grid(4, 4, [8.0], [1.0], [4.0, 4.0]).reshape(-1, 4)

    def loss_fn(params, img, gt, gtl, mask):
        head.load_tree(params)
        loc, conf = head(Tensor(img))
        return J.ssd_loss_jit(loc._value, conf._value, gt, gtl, mask,
                              priors)

    from paddle_tpu.models.nlp.train_utils import adamw_update

    @jax.jit
    def step(params, opt, t, img, gt, gtl, mask):
        loss, g = jax.value_and_grad(loss_fn)(params, img, gt, gtl, mask)
        new_p, new_o = {}, {}
        for k in params:
            new_p[k], m, v = adamw_update(
                params[k], g[k], opt[k][0], opt[k][1], t, lr=5e-3,
                beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0)
            new_o[k] = (m, v)
        return new_p, new_o, loss

    opt = {k: (jnp.zeros_like(v), jnp.zeros_like(v)) for k, v in
           params.items()}
    rng = np.random.default_rng(0)
    losses = []
    for i in range(60):
        img = rng.normal(0, 0.1, (1, 3, 16, 16)).astype(np.float32)
        cx = int(rng.integers(0, 4)) * 4 + 2
        img[0, :, 2:6, cx - 2:cx + 2] += 1.0
        gt = np.array([[cx - 2.0, 2.0, cx + 2.0, 6.0]], np.float32)
        params, opt, loss = step(params, opt, i + 1.0, img, gt,
                                 np.array([1], np.int64),
                                 np.ones(1, bool))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_host_api_tracer_dispatch():
    """The public host ops route to their jnp twins under jit — an
    existing eager pipeline composes into a compiled step unchanged."""
    from paddle_tpu.vision.detection import (box_clip, box_coder,
                                             iou_similarity)
    rng = np.random.default_rng(5)
    a, b = _rand_boxes(rng, 4), _rand_boxes(rng, 6)
    pv = np.array([0.1, 0.1, 0.2, 0.2], np.float32)

    @jax.jit
    def f(a, b):
        iou = iou_similarity(a, b)._value
        enc = box_coder(b, pv, a, "encode_center_size")._value
        clipped = box_clip(a, jnp.asarray([40.0, 40.0, 1.0]))._value
        return iou.sum() + enc.sum() + clipped.sum()

    want = (iou_similarity(a, b).numpy().sum()
            + box_coder(b, pv, a, "encode_center_size").numpy().sum()
            + box_clip(a, np.array([40.0, 40.0, 1.0])).numpy().sum())
    np.testing.assert_allclose(float(f(a, b)), want, rtol=1e-4)
