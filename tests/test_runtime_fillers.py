"""Runtime/platform fillers: memory stats, kernel autotune cache, graph
passes, spawn entry."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.framework import device as pdevice
from paddle_tpu.ops import autotune as at


class TestMemoryStats:
    def test_stats_shape(self):
        s = pdevice.memory_stats()
        assert isinstance(s, dict)
        x = paddle.ones([64, 64])
        assert pdevice.memory_allocated() >= 0
        assert pdevice.max_memory_allocated() >= pdevice.memory_allocated() \
            or pdevice.max_memory_allocated() == 0
        pdevice.empty_cache()
        pdevice.cuda.synchronize()
        assert pdevice.cuda.device_count() >= 1


class TestAutotune:
    def test_cache_and_selection(self):
        calls = {"slow": 0, "fast": 0}

        def slow(x):
            import time
            time.sleep(0.01)
            calls["slow"] += 1
            return x

        def fast(x):
            calls["fast"] += 1
            return x

        at.enable_autotune()
        try:
            args = (np.zeros((4, 4), np.float32),)
            import jax.numpy as jnp
            args = (jnp.zeros((4, 4)),)
            chosen = at.autotune("toy_op", [slow, fast], args)
            assert chosen is fast
            # second call hits the cache (no extra timing runs)
            before = calls["slow"]
            chosen2 = at.autotune("toy_op", [slow, fast], args)
            assert chosen2 is fast and calls["slow"] == before
            rep = at.cache().report()
            assert rep["size"] >= 1 and rep["hits"] >= 1
        finally:
            at.disable_autotune()

    def test_disabled_returns_default(self):
        def a(x):
            return x

        def b(x):
            return x
        assert not at.autotune_enabled()
        import jax.numpy as jnp
        assert at.autotune("toy2", [a, b], (jnp.zeros(1),)) is a

    def test_export_load(self, tmp_path):
        at.enable_autotune()
        try:
            import jax.numpy as jnp
            at.autotune("toy3", [lambda x: x, lambda x: x + 0],
                        (jnp.zeros(2),))
            p = str(tmp_path / "tune.json")
            at.cache().export(p)
            import json
            assert json.load(open(p))
        finally:
            at.disable_autotune()

    def test_set_config(self):
        from paddle_tpu.incubate import autotune as iat
        iat.set_config({"kernel": {"enable": True}})
        assert at.autotune_enabled()
        iat.set_config({"kernel": {"enable": False}})
        assert not at.autotune_enabled()


class TestPasses:
    def test_dce(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog, static.Program()):
                x = static.data("x", [2, 2], "float32")
                live = paddle.add(x, x)
                dead = paddle.multiply(x, x)   # never fetched
                dead2 = paddle.exp(dead)
            n_before = len(prog._vars)
            removed = static.apply_pass(prog, "dead_code_elimination",
                                        fetch_vars=[live])
            assert removed == 2
            assert len(prog._vars) == n_before - 2
            out = static.Executor().run(
                prog, feed={"x": np.ones((2, 2), np.float32)},
                fetch_list=[live])
            np.testing.assert_allclose(out[0], 2 * np.ones((2, 2)))
        finally:
            paddle.disable_static()

    def test_capture_folds_pure_constants(self):
        # non-symbolic subgraphs evaluate at capture time: building with
        # constants adds no program ops at all (folding by construction)
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog, static.Program()):
                c = paddle.ones([2])
                folded = paddle.exp(paddle.add(c, c))
            assert prog._n_ops == 0
            assert not hasattr(folded, "_symbolic") or \
                not folded._symbolic
        finally:
            paddle.disable_static()

    def test_constant_folding_after_freeze(self):
        from paddle_tpu.static.passes import freeze_feed
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog, static.Program()):
                x = static.data("x", [2], "float32")
                y = static.data("y", [2], "float32")
                frozen_branch = paddle.exp(paddle.add(x, x))
                out = paddle.add(y, frozen_branch)
            freeze_feed(x, np.ones(2, np.float32))
            n = static.apply_pass(prog, "constant_folding")
            assert n >= 2
            assert getattr(frozen_branch, "_const_value", None) is not None
            # runs WITHOUT feeding x — its subtree is now constant
            res = static.Executor().run(
                prog, feed={"y": np.zeros(2, np.float32)},
                fetch_list=[out])
            np.testing.assert_allclose(res[0], np.exp(2.0) * np.ones(2),
                                       rtol=1e-6)
        finally:
            paddle.disable_static()

    def test_op_stats_and_registry(self):
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog, static.Program()):
                x = static.data("x", [2], "float32")
                y = paddle.add(x, x)
                z = paddle.add(y, y)
            stats = static.apply_pass(prog, "op_stats")
            assert stats.get("add") == 2
            with pytest.raises(KeyError):
                static.apply_pass(prog, "not_a_pass")

            @static.register_pass("custom_noop")
            def custom(prog):
                return "ran"
            assert static.apply_pass(prog, "custom_noop") == "ran"
        finally:
            paddle.disable_static()


class TestSpawn:
    def test_spawn_api_exists(self):
        import paddle_tpu.distributed as dist
        assert callable(dist.spawn)


class TestTrainerLoops:
    def test_train_from_dataset(self, tmp_path):
        import paddle_tpu.distributed as dist
        import paddle_tpu.optimizer as popt
        from paddle_tpu import static
        paddle.enable_static()
        try:
            prog = static.Program()
            start = static.Program()
            with static.program_guard(prog, start):
                x = static.data("x", [4, 8], "float32")
                y = static.data("y", [4, 1], "float32")
                pred = static.nn.fc(x, 1)
                loss = paddle.mean(paddle.pow(pred - y, 2.0))
                popt.SGD(learning_rate=0.1).minimize(loss)
            exe = static.Executor()
            exe.run(start)
            rng = np.random.default_rng(0)
            w_true = rng.standard_normal(8).astype("f4")
            f = tmp_path / "train.txt"
            lines = []
            for _ in range(64):
                feat = rng.standard_normal(8).astype("f4")
                lines.append(" ".join(
                    map(str, feat.tolist() + [float(feat @ w_true)])))
            f.write_text("\n".join(lines))
            ds = dist.InMemoryDataset()
            ds.init(batch_size=4)
            ds.set_filelist([str(f)])
            ds.set_parse_fn(lambda line: (
                np.array(line.split()[:8], np.float32),
                np.array(line.split()[8:9], np.float32)))
            ds.load_into_memory()
            out = None
            for _ in range(5):
                out = exe.train_from_dataset(prog, ds, fetch_list=[loss])
            assert out[0] < 1.0  # converged on the linear target
        finally:
            paddle.disable_static()


class TestProfilerSummary:
    def test_summary_table(self):
        import paddle_tpu.profiler as prof
        with prof.profile() as p:
            a = paddle.randn([32, 32])
            for _ in range(2):
                a = paddle.matmul(a, a)
        table = p.summary()
        assert "op::matmul" in table
        assert "ratio" in table.splitlines()[0]


class TestCustomDevice:
    def test_fake_device_roundtrip(self):
        from paddle_tpu.framework import custom_device as cd
        cd.register_fake_device("my_npu", backend="cpu")
        try:
            assert cd.is_custom_device("my_npu")
            assert "my_npu" in cd.get_all_custom_device_type()
            assert cd.get_device_count("my_npu") >= 1
            assert len(cd.devices("my_npu")) >= 1
        finally:
            cd.unregister_custom_device("my_npu")
        assert not cd.is_custom_device("my_npu")

    def test_missing_plugin_rejected(self):
        from paddle_tpu.framework import custom_device as cd
        with pytest.raises(FileNotFoundError):
            cd.register_custom_device("ghost", "/nonexistent/plugin.so")
