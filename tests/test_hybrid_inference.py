"""HybridParallelInferenceHelper (pipelined inference over the carrier).

~ reference test_hybrid_parallel_inference_helper.py capability: staged
inference matches the unstaged forward.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.utils import HybridParallelInferenceHelper


class TestHelper:
    def test_pipelined_matches_plain(self):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.Softmax())
        m.eval()
        helper = HybridParallelInferenceHelper(model=m, num_pp=2,
                                               micro_batch_size=4)
        helper.gen_infer_program()
        x = np.random.default_rng(0).normal(0, 1, (10, 8)).astype(np.float32)
        out = helper.run(paddle.to_tensor(x))
        ref = m(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_single_stage(self):
        paddle.seed(1)
        m = nn.Sequential(nn.Linear(4, 4))
        m.eval()
        helper = HybridParallelInferenceHelper(model=m, num_pp=1)
        x = np.ones((3, 4), np.float32)
        out = helper.run(paddle.to_tensor(x))
        assert out.shape == [3, 4]
