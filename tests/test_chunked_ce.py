"""Chunked-vocabulary CE (ops/chunked_ce.py): loss without the logits.

~ the memory problem the reference addresses only via vocab-sharded
c_softmax_with_cross_entropy (TP); this is the single-chip form — the
(B*S, V) logits tensor never exists, the head matmul streams vocab
chunks through a lax.scan with online logsumexp, and the backward
recomputes each chunk's softmax (flash attention's trick on the vocab
axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.chunked_ce import chunked_causal_lm_loss


def _dense(x, w, lbl):
    lg = jnp.einsum("bsh,vh->bsv", x, w).astype(jnp.float32)
    lp = jax.nn.log_softmax(lg, -1)
    return -jnp.mean(jnp.take_along_axis(lp, lbl[..., None], -1))


class TestChunkedCE:
    @pytest.mark.parametrize("V,chunk", [(96, 32), (101, 32), (101, 128),
                                         (96, 96)])
    def test_matches_dense_loss_and_grads(self, V, chunk):
        rng = np.random.default_rng(0)
        B, S, H = 2, 16, 32
        x = jnp.asarray(rng.standard_normal((B, S, H)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((V, H)) * 0.3, jnp.float32)
        lbl = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        lc = chunked_causal_lm_loss(x, w, lbl, chunk)
        np.testing.assert_allclose(float(lc), float(_dense(x, w, lbl)),
                                   rtol=1e-6)
        gc = jax.grad(lambda a, b: chunked_causal_lm_loss(a, b, lbl,
                                                          chunk),
                      argnums=(0, 1))(x, w)
        gd = jax.grad(lambda a, b: _dense(a, b, lbl),
                      argnums=(0, 1))(x, w)
        for a, b in zip(gc, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_bf16_inputs(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((1, 8, 32)), jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((64, 32)) * 0.3, jnp.bfloat16)
        lbl = jnp.asarray(rng.integers(0, 64, (1, 8)), jnp.int32)
        lc = chunked_causal_lm_loss(x, w, lbl, 32)
        ld = _dense(x.astype(jnp.float32), w.astype(jnp.float32), lbl)
        assert abs(float(lc) - float(ld)) < 0.05
        dx, dw = jax.grad(
            lambda a, b: chunked_causal_lm_loss(a, b, lbl, 32),
            argnums=(0, 1))(x, w)
        assert dx.dtype == jnp.bfloat16 and dw.dtype == jnp.bfloat16

    def test_under_jit_and_grad_compose(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((1, 8, 16)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((48, 16)) * 0.3, jnp.float32)
        lbl = jnp.asarray(rng.integers(0, 48, (1, 8)), jnp.int32)
        f = jax.jit(lambda a, b: jax.value_and_grad(
            lambda a2, b2: chunked_causal_lm_loss(a2, b2, lbl, 16),
            argnums=(0, 1))(a, b))
        loss, (dx, dw) = f(x, w)
        assert np.isfinite(float(loss))
        assert np.isfinite(np.asarray(dx)).all()


class TestFactoryIntegration:
    def test_factory_loss_matches_standard_path(self):
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama import llama_train_step_factory

        cfg = LlamaConfig.tiny(vocab=101, hidden=32, layers=1, heads=2,
                               kv_heads=2)
        cfg.tie_word_embeddings = True
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        rng = np.random.default_rng(3)
        tok = jnp.asarray(rng.integers(0, 101, (2, 17)), jnp.int32)

        def one_step(**kw):
            paddle.seed(7)
            m = LlamaForCausalLM(cfg)
            p, o, step, _ = llama_train_step_factory(
                m, mesh, remat=False, **kw)
            _, _, loss = step(p, o, tok[:, :-1], tok[:, 1:])
            return float(loss)

        base = one_step()
        chunked = one_step(chunked_vocab_ce=32)
        assert abs(base - chunked) < 1e-4, (base, chunked)

    def test_moe_factory_chunked_matches_dense(self):
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import (MoEConfig, MoEForCausalLM,
                                           moe_train_step_factory)
        cfg = MoEConfig.deepseek_tiny()
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        rng = np.random.default_rng(4)
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)),
                          jnp.int32)

        def one_step(**kw):
            paddle.seed(9)
            m = MoEForCausalLM(cfg)
            p, o, step = moe_train_step_factory(m, mesh, **kw)
            _, _, loss = step(p, o, tok[:, :-1], tok[:, 1:])
            return float(loss)

        base = one_step()
        chunked = one_step(chunked_vocab_ce=96)  # 256 % 96 != 0: pad path
        assert abs(base - chunked) < 1e-4, (base, chunked)

    def test_rejects_model_axis_mesh(self):
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama import llama_train_step_factory
        cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=1, heads=2,
                               kv_heads=2)
        cfg.tie_word_embeddings = True
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2),
                    ("data", "model"))
        with pytest.raises(ValueError, match="model"):
            llama_train_step_factory(m, mesh, chunked_vocab_ce=32)

    def test_rejects_untied_head(self):
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama import llama_train_step_factory
        cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=1, heads=2,
                               kv_heads=2)
        cfg.tie_word_embeddings = False
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        with pytest.raises(ValueError, match="tied"):
            llama_train_step_factory(m, mesh, chunked_vocab_ce=32)
