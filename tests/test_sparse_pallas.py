"""Real sparse index/value kernels vs scipy oracles + fused Pallas kernels.

~ reference phi/kernels/sparse/ (matmul, elementwise, coalesce) tested the
OpTest way (numpy/scipy oracle, SURVEY.md §4), and the fused_ops rows
(fused_attention_op.cu softmax-xent / fused dropout+residual+LN).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import paddle_tpu as paddle
from paddle_tpu import sparse


def _rand_coo(m, n, nnz, seed=0):
    rng = np.random.default_rng(seed)
    lin = rng.choice(m * n, size=nnz, replace=False)
    rows, cols = np.unravel_index(lin, (m, n))
    vals = rng.standard_normal(nnz).astype(np.float32)
    st = sparse.sparse_coo_tensor(np.stack([rows, cols]), vals, [m, n])
    oracle = sp.coo_matrix((vals, (rows, cols)), shape=(m, n))
    return st, oracle


class TestSparseKernels:
    def test_spmm_vs_scipy(self):
        st, oracle = _rand_coo(16, 24, 60)
        y = np.random.default_rng(1).standard_normal((24, 8)).astype(
            np.float32)
        out = sparse.matmul(st, paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), oracle @ y, rtol=1e-5,
                                   atol=1e-5)

    def test_dense_at_sparse_vs_scipy(self):
        st, oracle = _rand_coo(16, 24, 60, seed=3)
        x = np.random.default_rng(2).standard_normal((8, 16)).astype(
            np.float32)
        out = sparse.matmul(paddle.to_tensor(x), st)
        np.testing.assert_allclose(out.numpy(), x @ oracle.toarray(),
                                   rtol=1e-5, atol=1e-5)

    def test_csr_matmul_vs_scipy(self):
        st, oracle = _rand_coo(12, 20, 40, seed=4)
        csr_o = oracle.tocsr()
        st_csr = sparse.sparse_csr_tensor(
            csr_o.indptr, csr_o.indices, csr_o.data, [12, 20])
        y = np.random.default_rng(5).standard_normal((20, 6)).astype(
            np.float32)
        out = sparse.matmul(st_csr, paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), csr_o @ y, rtol=1e-5,
                                   atol=1e-5)

    def test_masked_matmul(self):
        mask, _ = _rand_coo(10, 12, 30, seed=6)
        rng = np.random.default_rng(7)
        a = rng.standard_normal((10, 9)).astype(np.float32)
        b = rng.standard_normal((9, 12)).astype(np.float32)
        out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                                   mask)
        dense = a @ b
        idx = np.asarray(out.indices_.numpy())
        np.testing.assert_allclose(out.values_.numpy(),
                                   dense[idx[0], idx[1]], rtol=1e-5,
                                   atol=1e-5)

    def test_add_and_coalesce_vs_scipy(self):
        a, oa = _rand_coo(8, 8, 20, seed=8)
        b, ob = _rand_coo(8, 8, 20, seed=9)
        out = sparse.add(a, b)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   (oa + ob).toarray(), rtol=1e-5,
                                   atol=1e-5)

    def test_multiply_sparse_dense_keeps_pattern(self):
        a, oa = _rand_coo(8, 8, 20, seed=10)
        d = np.random.default_rng(11).standard_normal((8, 8)).astype(
            np.float32)
        out = sparse.multiply(a, paddle.to_tensor(d))
        assert out.nnz == a.nnz
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   oa.toarray() * d, rtol=1e-5, atol=1e-5)

    def test_transpose_and_format_conversion(self):
        a, oa = _rand_coo(6, 9, 15, seed=12)
        t = sparse.transpose(a, [1, 0])
        np.testing.assert_allclose(t.to_dense().numpy(), oa.T.toarray(),
                                   rtol=1e-6)
        csr = sparse.sparse_coo_to_csr(a)
        oc = oa.tocsr()
        np.testing.assert_allclose(np.asarray(csr.crows_.numpy()), oc.indptr)
        back = sparse.sparse_csr_to_coo(csr)
        np.testing.assert_allclose(back.to_dense().numpy(), oa.toarray(),
                                   rtol=1e-6)


class TestFusedCE:
    def test_forward_matches_dense(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((32, 128)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 128, (32,)), jnp.int32)
        from paddle_tpu.ops.pallas.fused_ce import softmax_cross_entropy
        loss = softmax_cross_entropy(logits, labels)
        logp = jax.nn.log_softmax(logits, -1)
        ref = -jnp.take_along_axis(logp, labels[:, None], -1)[:, 0]
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_dense(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((16, 64)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 64, (16,)), jnp.int32)
        from paddle_tpu.ops.pallas.fused_ce import softmax_cross_entropy

        g1 = jax.grad(lambda x: jnp.mean(
            softmax_cross_entropy(x, labels)))(logits)

        def dense(x):
            logp = jax.nn.log_softmax(x, -1)
            return jnp.mean(-jnp.take_along_axis(
                logp, labels[:, None], -1)[:, 0])

        g2 = jax.grad(dense)(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-6)

    def test_causal_lm_loss_wrapper(self):
        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((2, 8, 32)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 32, (2, 8)), jnp.int32)
        from paddle_tpu.ops.pallas.fused_ce import causal_lm_loss
        loss = causal_lm_loss(logits, labels)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        ref = jnp.mean(-jnp.take_along_axis(
            logp, labels[..., None], -1)[..., 0])
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


class TestFusedDropoutLN:
    def test_eval_mode_matches_dense_layernorm(self):
        from paddle_tpu.ops.pallas.dropout_ln import (
            fused_dropout_add_layer_norm)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(128), jnp.float32)
        b = jnp.asarray(rng.standard_normal(128), jnp.float32)
        out = fused_dropout_add_layer_norm(x, res, w, b, p=0.5,
                                           training=False)
        h = x + res
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        ref = (h - mu) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_train_mode_dropout_statistics(self):
        from paddle_tpu.ops.pallas.dropout_ln import (
            fused_dropout_add_layer_norm)
        paddle.seed(0)
        x = jnp.ones((128, 256), jnp.float32) * 3.0
        res = jnp.zeros((128, 256), jnp.float32)
        w = jnp.ones(256, jnp.float32)
        b = jnp.zeros(256, jnp.float32)
        p = 0.3
        bits = jax.random.bits(jax.random.PRNGKey(0), (128, 256),
                               jnp.uint32)
        out = fused_dropout_add_layer_norm(x, res, w, b, p=p, training=True,
                                           bits=bits)
        # dropout then LN of a constant input: surviving entries share one
        # positive value, dropped are another; just check drop fraction via
        # the pre-LN reconstruction
        u = np.asarray(bits).astype(np.float64) / 4294967296.0
        keep_frac = (u >= p).mean()
        assert abs(keep_frac - (1 - p)) < 0.02
        assert np.isfinite(np.asarray(out)).all()
