"""Window x sep composition: ring_window_attention vs the dense oracle.

Round-4 verdict item 5: sliding_window and context parallelism (the two
long-context features) must compose. The ring walks only the chunk
pairs the band touches; these tests check exact parity (fwd + grads)
against global dense windowed attention on the virtual CPU mesh,
including GQA head grouping and windows that skip ring steps.
"""
import numpy as np
import pytest


def _dense_window_oracle(q, k, v, window, sm_scale):
    """Global banded-causal attention in f64-ish f32 numpy."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    kf = np.repeat(k, G, axis=1)
    vf = np.repeat(v, G, axis=1)
    s = np.einsum("bhqd,bhkd->bhqk", q, kf).astype(np.float64) * sm_scale
    qp = np.arange(S)[:, None]
    kp = np.arange(S)[None, :]
    live = (qp >= kp) & ((qp - kp) < window)
    s = np.where(live, s, -1e30)
    m = s.max(-1, keepdims=True)
    p = np.exp(s - m)
    p = np.where(live, p, 0.0)
    l = p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p / np.maximum(l, 1e-30), vf)


def _mesh(n):
    import jax
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:n]), ("sep",))


@pytest.mark.parametrize("sep,S,window,Hq,Hkv", [
    (2, 64, 24, 2, 2),    # window inside one chunk: 1 active step of 2
    (4, 64, 24, 2, 2),    # window spans 2 chunks of 4: skip 2 steps
    (4, 64, 48, 4, 2),    # GQA + window spanning 3 chunks
    (2, 64, 64, 2, 1),    # window == S degenerates to full causal, MQA
])
def test_ring_window_matches_dense_oracle(sep, S, window, Hq, Hkv):
    import jax.numpy as jnp
    from paddle_tpu.parallel.ring_attention import ring_window_attention
    rng = np.random.default_rng(0)
    B, D = 2, 16
    q = rng.standard_normal((B, Hq, S, D)).astype(np.float32)
    k = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    v = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    sm = 1.0 / np.sqrt(D)
    ref = _dense_window_oracle(q, k, v, window, sm)
    out = ring_window_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), _mesh(sep), window)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_ring_window_skips_out_of_band_steps():
    from paddle_tpu.parallel.ring_attention import ring_window_active_steps
    # S=8192, sep=4 -> Sloc=2048; window=2048 touches distance 0 and 1
    # (queries at a chunk start still see the previous chunk's tail)
    assert ring_window_active_steps(4, 2048, 2048) == 2
    assert ring_window_active_steps(4, 1024, 2048) == 2
    # window covering everything: full ring
    assert ring_window_active_steps(4, 8192, 2048) == 4
    # distance-2 pairs only come live once window exceeds Sloc + 1
    assert ring_window_active_steps(4, 2050, 2048) == 3


def test_ring_window_degenerate_window_runs_one_step():
    """window <= 1: only the diagonal (distance 0) can hold a live
    pair — the nearest cross-chunk pair has gap 1, dead for window 1.
    The old formula overshot by one, running a fully-masked splash call
    + ppermute (round-5 advice #1)."""
    from paddle_tpu.parallel.ring_attention import ring_window_active_steps
    assert ring_window_active_steps(4, 1, 2048) == 1
    assert ring_window_active_steps(4, 0, 2048) == 1
    assert ring_window_active_steps(1, 1, 64) == 1
    # window 2 genuinely needs the distance-1 step (gap 1 < 2)
    assert ring_window_active_steps(4, 2, 2048) == 2
    # and a window-1 ring still computes the right thing (diagonal-only
    # attention == each position attends itself)
    import jax.numpy as jnp
    from paddle_tpu.parallel.ring_attention import ring_window_attention
    rng = np.random.default_rng(5)
    q = rng.standard_normal((1, 2, 32, 8)).astype(np.float32)
    k = rng.standard_normal((1, 2, 32, 8)).astype(np.float32)
    v = rng.standard_normal((1, 2, 32, 8)).astype(np.float32)
    out = ring_window_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), _mesh(2), 1)
    ref = _dense_window_oracle(q, k, v, 1, 1.0 / np.sqrt(8))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_splash_bwd_precomputed_delta_matches(monkeypatch):
    """_splash_bwd's optional precomputed-delta kwarg (the ring hoists
    sum(dO*O) out of its per-step loop) must be bit-identical to the
    in-function reduction."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.splash_attention import (
        _splash_bwd, _splash_fwd, banded_block_mask)
    rng = np.random.default_rng(3)
    B, H, S, D, W = 1, 2, 256, 64, 96
    bq = bk = 128
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    do = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    bm = banded_block_mask(S, S, bq, bk, W)
    sm = 1.0 / np.sqrt(D)
    out, res = _splash_fwd(q, k, v, bm, True, sm, bq, bk, W, 0)
    lse = res[4]
    inner = _splash_bwd(bm, True, sm, bq, bk, W, 0,
                        (q, k, v, out, lse), do)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    hoisted = _splash_bwd(bm, True, sm, bq, bk, W, 0,
                          (q, k, v, out, lse), do, delta=delta)
    for a, b, name in zip(inner, hoisted, ("dq", "dk", "dv")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_ring_window_grads_match_dense_oracle():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.ring_attention import ring_window_attention
    rng = np.random.default_rng(1)
    B, Hq, Hkv, S, D, W, sep = 1, 2, 1, 64, 16, 24, 4
    q = rng.standard_normal((B, Hq, S, D)).astype(np.float32)
    k = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    v = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    mesh = _mesh(sep)
    sm = 1.0 / np.sqrt(D)

    def ring_loss(q, k, v):
        out = ring_window_attention(q, k, v, mesh, W)
        return jnp.sum(out * out)

    def dense_loss(q, k, v):
        G = Hq // Hkv
        kf = jnp.repeat(k, G, axis=1)
        vf = jnp.repeat(v, G, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kf) * sm
        qp = jnp.arange(S)[:, None]
        kp = jnp.arange(S)[None, :]
        live = (qp >= kp) & ((qp - kp) < W)
        s = jnp.where(live, s, -1e30)
        p = jax.nn.softmax(s, -1)
        p = jnp.where(live, p, 0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        return jnp.sum(out * out)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, err_msg=f"d{name}")


def test_llama_window_on_sep_mesh_matches_single_device():
    """Model-level: a sliding-window Llama forward on a sep=2 mesh must
    equal the same model on one device (the round-4 ValueError path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama import llama_train_step_factory

    cfg = LlamaConfig.tiny(vocab=128, hidden=32, layers=2, heads=4)
    cfg.sliding_window = 8
    paddle.seed(3)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    ref = np.asarray(model(paddle.to_tensor(tok.copy()))._value)

    mesh = _mesh(2)
    params = {k: v._value for k, v in model.state_dict().items()}

    def fwd(params, tokens):
        model.load_tree(params)
        return model(Tensor(tokens))._value

    with mesh:
        out = jax.jit(fwd)(params, jnp.asarray(tok))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4,
                               rtol=2e-4)

    # and the full train step runs on the sep mesh
    paddle.seed(3)
    m2 = LlamaForCausalLM(cfg)
    p, o, step, _ = llama_train_step_factory(m2, mesh, remat=False)
    _, _, loss = step(p, o, jnp.asarray(tok), jnp.asarray(tok))
    assert np.isfinite(float(loss))


def test_ring_window_splash_engine_interpret(monkeypatch):
    """Splash-engine path (the one real TPU sep training takes) vs the
    dense oracle in interpret mode — validates the q_offset
    shifted-frame kernels, the online lse merge, the custom-VJP ring
    backward and the early dK/dV homing permute. CPU's flash_eligible
    gate is forced open so this does NOT silently take the dense
    fallback (round-5 review finding)."""
    import jax
    import jax.numpy as jnp

    import sys as _sys

    import paddle_tpu.ops.pallas.flash_attention  # noqa: F401
    from paddle_tpu.parallel.ring_attention import ring_window_attention
    fa_mod = _sys.modules["paddle_tpu.ops.pallas.flash_attention"]
    monkeypatch.setattr(fa_mod, "flash_eligible",
                        lambda *a, **kw: True)
    rng = np.random.default_rng(2)
    B, Hq, Hkv, S, D, W, sep = 1, 2, 2, 512, 64, 160, 4
    q = rng.standard_normal((B, Hq, S, D)).astype(np.float32)
    k = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    v = rng.standard_normal((B, Hkv, S, D)).astype(np.float32)
    sm = 1.0 / np.sqrt(D)
    mesh = _mesh(sep)
    # Sloc=128, window=160 -> 3 active ring steps of 4 (tests both the
    # cross-chunk pairs AND the skipped step + homing permute)
    out = ring_window_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), mesh, W)
    ref = _dense_window_oracle(q, k, v, W, sm)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-2, rtol=2e-2)

    def ring_loss(q, k, v):
        o = ring_window_attention(q, k, v, mesh, W)
        return jnp.sum(o * o)

    def dense_loss(q, k, v):
        G = Hq // Hkv
        kf = jnp.repeat(k, G, axis=1)
        vf = jnp.repeat(v, G, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kf) * sm
        qp = jnp.arange(S)[:, None]
        kp = jnp.arange(S)[None, :]
        live = (qp >= kp) & ((qp - kp) < W)
        s = jnp.where(live, s, -1e30)
        p = jax.nn.softmax(s, -1)
        p = jnp.where(live, p, 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        return jnp.sum(o * o)

    g = jax.grad(ring_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b, name in zip(g, gd, "qkv"):
        scale = max(1e-3, float(jnp.abs(b).max()))
        err = float(jnp.abs(a - b).max()) / scale
        assert err < 5e-2, f"d{name} rel err {err}"
