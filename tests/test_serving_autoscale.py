"""SLO-driven elastic autoscaling: the detect->act control plane.

Covers the Autoscaler policy (burn-triggered joins with incident
closure, low-utilization drains with hysteresis/cooldowns, the
crashed-drain loud noop, generation-suffixed standby recycling, role
rebalance), the QoSScheduler incident-degradation tier actuation, the
``serving_replica_busy_frac`` signal, the diurnal/flash-crowd trace
synthesizers, byte-identity with the autoscaler off, action-log
determinism, replica-hours accounting, and the ``serving_autoscale``
bench-gate family (pass + loud FAIL rows).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.obs import default_serving_rules  # noqa: E402
from paddle_tpu.obs.slo import (IncidentLog, SLOMonitor,  # noqa: E402
                                ThresholdRule)
from paddle_tpu.serving import (AutoscaleConfig, Autoscaler,  # noqa: E402
                                ClusterRouter, FailoverConfig,
                                FaultEvent, FaultPlan, QoSScheduler,
                                Request, ServiceEstimator,
                                ServingEngine, count_oscillations,
                                load_trace, make_sim_serving,
                                save_trace, synthesize_diurnal_trace,
                                synthesize_flash_crowd_trace,
                                synthesize_prefill_heavy_trace,
                                synthesize_trace)

SLOTS, PS, ML, CHUNK = 8, 8, 64, 4
COSTS = {"prefill_unit": 1.0, "decode": 1.0}
WEIGHTS = {"intl": 2.0, "std": 1.0, "bulk": 0.5}
CAP6 = 6 * 8.0 / (1.5 + 8.0 / (SLOTS * CHUNK))  # 6-replica fleet
RULES = dict(long_window=200.0, short_window=40.0, min_events=60,
             burn_threshold=2.5)


def _spawn_qos(name, degrade=0.75):
    return ServingEngine(
        serving=make_sim_serving(max_len=ML, page_size=PS, slots=SLOTS,
                                 vocab=509,
                                 n_pool_pages=SLOTS * (ML // PS) + 9),
        slots=SLOTS, policy="paged", clock="fixed", fixed_costs=COSTS,
        decode_chunk=CHUNK,
        scheduler=QoSScheduler(max_queue=4 * SLOTS,
                               tenant_weights=WEIGHTS,
                               incident_degrade=degrade))


def _spawn_fifo(name, slots=4, max_len=96):
    return ServingEngine(
        serving=make_sim_serving(max_len=max_len, page_size=PS,
                                 slots=slots, vocab=509,
                                 n_pool_pages=slots * (max_len // PS)
                                 + 17),
        slots=slots, policy="paged", clock="fixed", fixed_costs=COSTS,
        decode_chunk=CHUNK)


def _asc(**over):
    kw = dict(standby=("s0", "s1", "s2", "s3"), min_replicas=2,
              max_replicas=8, interval=10.0, join_cooldown=30.0,
              drain_cooldown=120.0, hold_after_join=150.0,
              hold_after_drain=40.0, drain_sustain=120.0,
              drain_below=0.5, recover_sustain=120.0)
    kw.update(over)
    return Autoscaler(AutoscaleConfig(**kw))


def _flash(n=2000, seed=0):
    return synthesize_flash_crowd_trace(
        seed=seed, n_requests=n, service_tokens_per_unit=CAP6,
        base_overload=0.55, spikes=((0.55, 0.08, 4.0),))


# --- workload synthesizers --------------------------------------------------

def test_diurnal_trace_deterministic_and_shaped(tmp_path):
    a = synthesize_diurnal_trace(seed=3, n_requests=1500,
                                 service_tokens_per_unit=CAP6)
    b = synthesize_diurnal_trace(seed=3, n_requests=1500,
                                 service_tokens_per_unit=CAP6)
    assert a == b
    p = str(tmp_path / "d.jsonl")
    save_trace(p, a)
    assert load_trace(p) == a
    # the rate profile is real: the mid-span (peak) third carries far
    # more arrivals than the edge (trough) thirds combined per unit
    span = a[-1].arrival - a[0].arrival
    t0 = a[0].arrival
    thirds = [0, 0, 0]
    for r in a:
        thirds[min(2, int(3 * (r.arrival - t0) / (span + 1e-9)))] += 1
    assert thirds[1] > 1.5 * max(thirds[0], thirds[2])


def test_flash_trace_spike_density(tmp_path):
    tr = _flash(n=3000)
    assert tr == _flash(n=3000)
    span = tr[-1].arrival - tr[0].arrival
    t0 = tr[0].arrival
    in_spike = sum(1 for r in tr
                   if 0.55 <= (r.arrival - t0) / span < 0.63)
    # spike window (8% of span at 4x rate) holds ~4x its uniform share
    assert in_spike > 2.5 * 0.08 * len(tr)
    p = str(tmp_path / "f.jsonl")
    save_trace(p, tr)
    assert load_trace(p) == tr


def test_trace_synthesizer_validation():
    with pytest.raises(ValueError, match="trough"):
        synthesize_diurnal_trace(trough=0.0)
    with pytest.raises(ValueError, match="spike"):
        synthesize_flash_crowd_trace(spikes=((1.2, 0.1, 2.0),))
    with pytest.raises(ValueError, match="spike"):
        synthesize_flash_crowd_trace(spikes=((0.1, 0.1, 0.5),))


# --- config + lifecycle validation ------------------------------------------

def test_autoscale_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="interval"):
        AutoscaleConfig(interval=0.0)
    with pytest.raises(ValueError, match="join_above"):
        AutoscaleConfig(drain_below=0.9, join_above=0.8)
    with pytest.raises(ValueError, match="drain_sustain"):
        AutoscaleConfig(drain_sustain=-1.0)
    with pytest.raises(ValueError, match="scale_severity"):
        AutoscaleConfig(scale_severity="critical")
    with pytest.raises(ValueError, match="prefill_lo"):
        AutoscaleConfig(prefill_lo=9.0, prefill_hi=3.0)
    with pytest.raises(ValueError, match="not both"):
        Autoscaler(AutoscaleConfig(), interval=5.0)


def test_autoscaler_attach_once_and_requires_slo():
    asc = _asc()
    ClusterRouter(_spawn_fifo, 2, slo=[], autoscale=asc)
    with pytest.raises(RuntimeError, match="fresh one"):
        ClusterRouter(_spawn_fifo, 2, slo=[], autoscale=asc)
    with pytest.raises(ValueError, match="needs slo="):
        ClusterRouter(_spawn_fifo, 2, autoscale=_asc())
    with pytest.raises(ValueError, match="Autoscaler"):
        ClusterRouter(_spawn_fifo, 2, slo=[], autoscale="yes")


# --- the decide() unit surface ----------------------------------------------

class _FakeSession:
    def __init__(self, slots=8, free=8, crashed=False, load=0,
                 backlog=0, sheds=0):
        self.eng = type("E", (), {"slots": slots})()
        self._free = free
        self.crashed = crashed
        self._load = load
        self._backlog = backlog
        self.shed_log = {f"x{i}": "r" for i in range(sheds)}

    def free_slot_count(self):
        return self._free

    def load(self):
        return self._load

    def prefill_backlog(self):
        return self._backlog


class _FakeRep:
    def __init__(self, name, index, sess, role="both", admitting=True):
        self.name = name
        self.index = index
        self.session = sess
        self.role = role
        self.admitting = admitting


def _incident(log=None, kind="burn_rate", severity="page", t=0.0):
    log = log if log is not None else IncidentLog()
    return log.open(rule="deadline_burn", kind=kind, severity=severity,
                    t=t, source="r0")


def test_incident_storm_inside_cooldown_takes_one_join():
    asc = _asc(join_cooldown=30.0)
    reps = [_FakeRep("r0", 0, _FakeSession(free=0)),
            _FakeRep("r1", 1, _FakeSession(free=0))]
    log = IncidentLog()
    # a storm: five incidents land before the first tick
    incs = [_incident(log, t=float(i)) for i in range(5)]
    for i in incs:
        asc.note_incident(i)
    acts = asc.decide(10.0, reps, lambda b: b)
    assert [a["action"] for a in acts] == ["join"]
    # every open scale incident was closed by THE one action
    assert all(i.resolution == "action_taken" for i in incs)
    assert all(i.evidence["action_taken"].startswith("join:")
               for i in incs)
    # more incidents inside the cooldown: NO duplicate action
    for i in range(3):
        asc.note_incident(_incident(log, t=12.0 + i))
    assert asc.decide(20.0, reps, lambda b: b) == []
    assert asc.decide(30.0, reps, lambda b: b) == []
    # cooldown passed (first join at t=10): the next one may land
    acts = asc.decide(40.0, reps, lambda b: b)
    assert [a["action"] for a in acts] == ["join"]
    assert asc.summary()["joins"] == 2


def test_join_respects_max_replicas_and_standby():
    asc = _asc(standby=("s0",), max_replicas=3)
    reps = [_FakeRep(f"r{i}", i, _FakeSession(free=0))
            for i in range(3)]
    asc.note_incident(_incident())
    assert asc.decide(10.0, reps, lambda b: b) == []  # at the cap
    asc2 = _asc(standby=(), max_replicas=8)
    asc2.note_incident(_incident())
    assert asc2.decide(10.0, reps, lambda b: b) == []  # pool empty


def test_drain_needs_sustained_low_util_and_hysteresis():
    asc = _asc(drain_sustain=50.0, hold_after_join=150.0,
               drain_cooldown=20.0, recover_sustain=20.0)
    reps = [_FakeRep(f"r{i}", i, _FakeSession(free=8))
            for i in range(4)]
    # idle from t=10, but the sustain window must elapse first
    assert asc.decide(10.0, reps, lambda b: b) == []
    assert asc.decide(40.0, reps, lambda b: b) == []
    acts = asc.decide(60.0, reps, lambda b: b)
    assert [a["action"] for a in acts] == ["drain"]
    # the drained base name returned to the standby pool
    assert asc.standby_available()[-1] == acts[0]["replica"]
    # a join resets the hysteresis: no drain inside hold_after_join
    asc2 = _asc(drain_sustain=10.0, hold_after_join=100.0,
                join_cooldown=1.0, hold_after_drain=0.0,
                recover_sustain=20.0)
    inc = _incident()
    asc2.note_incident(inc)
    a1 = asc2.decide(10.0, reps, lambda b: b)
    assert [a["action"] for a in a1] == ["join"]
    # calm after the join (recover_sustain passes, util zero) — but
    # the hold window keeps drains off until t >= 110
    assert all(a["action"] != "drain"
               for t in (40.0, 80.0, 100.0)
               for a in asc2.decide(t, reps, lambda b: b))
    acts = asc2.decide(120.0, reps, lambda b: b)
    assert [a["action"] for a in acts] == ["drain"]
    assert count_oscillations(asc2.actions, 100.0) == 0


def test_min_replicas_floor_holds():
    asc = _asc(min_replicas=2, drain_sustain=10.0, drain_cooldown=5.0)
    reps = [_FakeRep(f"r{i}", i, _FakeSession(free=8))
            for i in range(2)]
    for t in (20.0, 40.0, 80.0, 160.0):
        assert asc.decide(t, reps, lambda b: b) == []


def test_shed_pressure_carries_armed_episode():
    """One burn incident opens the episode; continued SHEDDING (not a
    new incident) keeps joins coming until the loss stops."""
    asc = _asc(join_cooldown=10.0, recover_sustain=30.0)
    sess = [_FakeSession(free=4, sheds=0) for _ in range(2)]
    reps = [_FakeRep(f"r{i}", i, s) for i, s in enumerate(sess)]
    asc.note_incident(_incident())
    a1 = asc.decide(10.0, reps, lambda b: b, sheds_total=0)
    assert [a["action"] for a in a1] == ["join"]
    # incident closed by the join — but sheds keep climbing
    a2 = asc.decide(20.0, reps, lambda b: b, sheds_total=5)
    assert [a["action"] for a in a2] == ["join"]
    assert a2[0]["reason"] == "armed_shedding"
    # calm (no new sheds) for recover_sustain: the episode disarms
    assert asc.decide(30.0, reps, lambda b: b, sheds_total=5) == []
    assert asc.decide(70.0, reps, lambda b: b, sheds_total=5) == []
    assert asc._armed is False


# --- cluster integration ----------------------------------------------------

def test_flash_crowd_joins_and_incident_closure():
    tr = _flash(n=2000)
    asc = _asc(standby=("s0", "s1", "s2"), min_replicas=4,
               max_replicas=7)
    res = ClusterRouter(_spawn_qos, 4, placement="least_loaded",
                        slo=default_serving_rules(**RULES),
                        autoscale=asc).run(tr)
    a = res.autoscale
    assert a["joins"] >= 1 and a["degrades"] >= 1
    acted = [i for i in res.incidents
             if i.resolution == "action_taken"]
    assert acted and all("action_taken" in i.evidence for i in acted)
    cen = res.census()
    assert cen["conserved"] and cen["pool_census_ok"]
    assert count_oscillations(a["actions"],
                              asc.cfg.hold_after_join) == 0
    # autoscale events mirrored into the router's event log
    assert any(e["event"] == "autoscale" and e.get("action") == "join"
               for e in res.events)
    # the joiners actually served work
    joined = [x["replica"] for x in a["actions"]
              if x["action"] == "join"]
    assert any(len(res.results[n].outputs) > 0 for n in joined)


def test_end_of_span_spike_acts_past_last_arrival():
    # a spike at the very END of the span: the burn incident opens
    # with (almost) no arrival ticks left, so the control plane must
    # CHAIN ticks past t_last while the backlog drains — before that
    # tail extension existed, the second join below was structurally
    # impossible (no tick in the heap after the last arrival) and the
    # incident sat open and unanswered
    tr = synthesize_flash_crowd_trace(
        seed=3, n_requests=1500, service_tokens_per_unit=CAP6,
        base_overload=0.55, spikes=((0.96, 0.04, 8.0),))
    t_last = max(r.arrival for r in tr)
    res = ClusterRouter(_spawn_qos, 2, placement="least_loaded",
                        slo=default_serving_rules(**RULES),
                        autoscale=_asc(min_replicas=2,
                                       max_replicas=8)).run(tr)
    a = res.autoscale
    assert a["joins"] >= 2
    assert any(x["t"] > t_last for x in a["actions"])
    assert res.census()["conserved"]


def test_autoscale_off_byte_identity():
    tr = _flash(n=1200)
    p1 = ClusterRouter(_spawn_qos, 3, placement="least_loaded").run(tr)
    p2 = ClusterRouter(_spawn_qos, 3, placement="least_loaded",
                       slo=default_serving_rules(**RULES)).run(tr)
    assert p1.outputs() == p2.outputs()
    assert {n: p1.results[n].slot_log for n in p1.results} \
        == {n: p2.results[n].slot_log for n in p2.results}
    assert {n: p1.results[n].metrics.request_rows()
            for n in p1.results} \
        == {n: p2.results[n].metrics.request_rows()
            for n in p2.results}
    assert p1.autoscale is None and p2.autoscale is None


def test_action_log_deterministic_and_save(tmp_path):
    tr = _flash(n=2000)

    def run():
        return ClusterRouter(
            _spawn_qos, 4, placement="least_loaded",
            slo=default_serving_rules(**RULES),
            autoscale=_asc(standby=("s0", "s1", "s2"),
                           min_replicas=4, max_replicas=7)).run(tr)

    r1, r2 = run(), run()
    assert r1.autoscale["actions"], "vacuous: the loop never acted"
    assert json.dumps(r1.autoscale["actions"]) \
        == json.dumps(r2.autoscale["actions"])
    assert r1.outputs() == r2.outputs()
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    r1.save_actions(pa)
    r2.save_actions(pb)
    with open(pa, "rb") as f:
        ba = f.read()
    with open(pb, "rb") as f:
        bb = f.read()
    assert ba == bb and len(ba) > 0


def test_save_actions_requires_autoscaler():
    tr = synthesize_trace(seed=0, n_requests=6, vocab_size=509,
                          prompt_len=(4, 8), output_len=(2, 4))
    res = ClusterRouter(_spawn_fifo, 2).run(tr)
    with pytest.raises(ValueError, match="no action log"):
        res.save_actions("/tmp/never.jsonl")


def test_drain_decision_on_crashed_replica_noops_loudly():
    """A drain decision landing on a mid-crash-failover replica must
    noop LOUDLY (action + event logged), leave the removal to the
    failover, and conserve the census."""
    # three replicas each holding one LONG decode; r1 crashes at t=5
    # and stays undetected (huge heartbeat timeout). Its salvaged
    # rows leave it with load 0, so the idlest-pick lands exactly on
    # the corpse while r0/r2 still stream. degrade=False so the
    # crash's own page incident does not suppress the drain timer
    # (with tier actuation on, an open page incident blocks drains —
    # the corpse is then shielded by its own crash alert).
    tr = [Request(rid=f"q{i}", arrival=0.1 * i, prompt=(1, 2, 3, 4),
                  max_new_tokens=60) for i in range(3)]
    asc = _asc(standby=(), min_replicas=1, interval=1.0,
               drain_below=0.9, join_above=0.95, drain_sustain=3.5,
               drain_cooldown=2.0, hold_after_join=0.0,
               hold_after_drain=0.0, recover_sustain=1.0,
               degrade=False)
    plan = FaultPlan([FaultEvent(t=5.0, kind="crash", replica="r1")])
    res = ClusterRouter(
        _spawn_fifo, 3, placement="least_loaded", faults=plan,
        failover=FailoverConfig(heartbeat_timeout=120.0,
                                heartbeat_interval=60.0),
        slo=[], autoscale=asc).run(tr)
    noops = [a for a in res.autoscale["actions"]
             if a["action"] == "drain_noop_crashed"]
    assert noops and noops[0]["replica"] == "r1"
    assert any(e["event"] == "autoscale"
               and e.get("action") == "drain_noop_crashed"
               for e in res.events)
    # the failover (not the drain) removed it, exactly once
    assert any(e["event"] == "dead" and e["replica"] == "r1"
               for e in res.events)
    assert res.census()["conserved"]


def test_standby_name_recycling_and_direct_join_refusal():
    r = ClusterRouter(_spawn_fifo, 1, slo=[], autoscale=_asc())
    assert r._standby_name("s0") == "s0"
    r.results["s0"] = object()          # a retired s0
    assert r._standby_name("s0") == "s0#2"
    r.results["s0#2"] = object()
    assert r._standby_name("s0") == "s0#3"
    # the PR-6 refusal is untouched for DIRECT joins of retired names
    r2 = ClusterRouter(_spawn_fifo, 1)
    r2.results["r9"] = object()
    with pytest.raises(ValueError, match="fresh name"):
        r2._add_replica("r9", 0.0)


def test_standby_recycle_full_loop():
    """Two flash spikes: the replica joined for spike 1 drains in the
    calm between them, returns to the pool, and rejoins for spike 2
    under a generation suffix — census still exactly-once."""
    tr = synthesize_flash_crowd_trace(
        seed=0, n_requests=2600, service_tokens_per_unit=CAP6,
        base_overload=0.5, spikes=((0.2, 0.06, 4.0), (0.7, 0.06, 4.0)))
    asc = _asc(standby=("s0",), min_replicas=4, max_replicas=5,
               interval=10.0, join_cooldown=30.0, drain_cooldown=60.0,
               hold_after_join=80.0, hold_after_drain=20.0,
               drain_sustain=60.0, drain_below=0.6,
               recover_sustain=60.0)
    res = ClusterRouter(_spawn_qos, 4, placement="least_loaded",
                        slo=default_serving_rules(**RULES),
                        autoscale=asc).run(tr)
    joined = [a["replica"] for a in res.autoscale["actions"]
              if a["action"] == "join"]
    recycled = [n for n in joined if "#" in n]
    assert recycled, (joined, res.autoscale["actions"])
    base = recycled[0].split("#", 1)[0]
    # the base name served (and retired) earlier in the SAME run, and
    # the recycled generation banked its own result slot
    assert base in res.results and recycled[0] in res.results
    cen = res.census()
    assert cen["conserved"] and cen["removal_census_ok"]


def test_replica_hours_accounting():
    tr = _flash(n=1500)
    res = ClusterRouter(_spawn_qos, 4, placement="least_loaded",
                        slo=default_serving_rules(**RULES),
                        autoscale=_asc(standby=("s0", "s1"),
                                       min_replicas=4,
                                       max_replicas=6)).run(tr)
    hours = res.replica_hours
    assert set(hours) == set(res.results)
    for h in hours.values():
        assert h["left"] is not None and h["left"] >= h["joined"]
        assert h["hours"] == round(h["left"] - h["joined"], 6)
    total = res.replica_hours_total()
    assert total == round(sum(h["hours"] for h in hours.values()), 6)
    assert res.report(tenant_weights=WEIGHTS)["replica_hours"] == total
    # a late joiner accrues strictly fewer hours than a founder
    joined = [a["replica"] for a in res.autoscale["actions"]
              if a["action"] == "join"]
    if joined:
        assert hours[joined[0]]["hours"] < hours["r0"]["hours"]


# --- role rebalance ---------------------------------------------------------

def test_role_rebalance_flips_decode_to_prefill():
    tr = synthesize_prefill_heavy_trace(seed=0, n_short=40, n_long=24,
                                        burst_size=8, vocab_size=509)

    def spawn(name):
        return ServingEngine(
            serving=make_sim_serving(max_len=96, page_size=PS, slots=4,
                                     vocab=509,
                                     n_pool_pages=4 * (96 // PS) + 17),
            slots=4, policy="paged", clock="fixed", fixed_costs=COSTS,
            decode_chunk=CHUNK, prefill_chunk_budget=2)

    asc = _asc(standby=(), min_replicas=1, interval=5.0,
               role_rebalance=True, role_cooldown=30.0,
               prefill_hi=6.0, prefill_lo=0.5)
    roles = {"r0": "prefill", "r1": "decode", "r2": "decode",
             "r3": "decode"}
    res = ClusterRouter(spawn, 4, placement="disaggregated",
                        roles=roles, kv_transfer_unit=0.05, slo=[],
                        autoscale=asc).run(tr)
    flips = [a for a in res.autoscale["actions"]
             if a["action"] == "role"]
    assert flips and flips[0]["from"] == "decode" \
        and flips[0]["to"] == "prefill" \
        and flips[0]["reason"] == "prefill_backlog_high"
    # cooldown: consecutive flips are >= role_cooldown apart
    for x, y in zip(flips, flips[1:]):
        assert y["t"] - x["t"] >= 30.0 - 1e-9
    cen = res.census()
    assert cen["conserved"]
    assert cen["handoffs"]["balanced"] and not cen["handoffs"]["failed"]


def test_role_rebalance_inert_without_dedicated_roles():
    tr = synthesize_trace(seed=0, n_requests=60, vocab_size=509,
                          prompt_len=(4, 10), output_len=(3, 6),
                          mean_interarrival=0.2)
    asc = _asc(standby=(), min_replicas=1, role_rebalance=True,
               prefill_hi=0.5, prefill_lo=0.1)
    res = ClusterRouter(_spawn_fifo, 3, slo=[], autoscale=asc).run(tr)
    assert res.autoscale["role_changes"] == 0


# --- QoS tier actuation -----------------------------------------------------

def test_incident_degrade_clamps_then_lifts():
    sched = QoSScheduler(incident_degrade=0.5, degrade_tiers=(1.0,))
    est = ServiceEstimator(prefill=1.0, decode=1.0)
    log = IncidentLog()
    inc = _incident(log, t=5.0)
    sched.note_incident(inc)
    # deadline-free request: clamped to half its budget while open
    sched.enqueue(Request(rid="a", arrival=0.0, prompt=(1, 2),
                          max_new_tokens=8), 0.0)
    dec = sched.select(10.0, max_batch=4, est=est)
    assert dec.wave[0].max_new_tokens == 4
    assert dec.degraded["a"] == (4, 8)
    sched.commit("a", 4)
    # incident closes -> the clamp lifts
    inc.close(20.0, "burn_recovered")
    sched.enqueue(Request(rid="b", arrival=21.0, prompt=(1, 2),
                          max_new_tokens=8), 21.0)
    dec2 = sched.select(22.0, max_batch=4, est=est)
    assert dec2.wave[0].max_new_tokens == 8 and not dec2.degraded


def test_incident_degrade_prefers_clamp_over_shed():
    """A request infeasible at full budget but feasible at the
    incident tier is DEGRADED, not shed — the flip-before-shed
    contract."""
    est = ServiceEstimator(prefill=1.0, decode=1.0)
    req = Request(rid="t", arrival=0.0, prompt=(1,),
                  max_new_tokens=10, deadline_ms=9000.0)
    # full budget: 1 + 10*1*1.5 = 16 > 9 -> shed without the tier
    plain = QoSScheduler(degrade_tiers=(1.0,))
    plain.enqueue(req, 0.0)
    d0 = plain.select(0.0, max_batch=4, est=est)
    assert not d0.wave and d0.shed
    hot = QoSScheduler(degrade_tiers=(1.0,), incident_degrade=0.5)
    hot.note_incident(_incident(t=0.0))
    hot.enqueue(req, 0.0)
    d1 = hot.select(0.0, max_batch=4, est=est)
    # tier 0.5: 1 + 5*1.5 = 8.5 <= 9 -> admitted short
    assert d1.wave and d1.wave[0].max_new_tokens == 5 and not d1.shed


def test_incident_degrade_default_inert():
    est = ServiceEstimator(prefill=1.0, decode=1.0)
    a = QoSScheduler()
    b = QoSScheduler()
    b.note_incident(_incident())  # recorded, never actuated
    for s in (a, b):
        s.enqueue(Request(rid="x", arrival=0.0, prompt=(1, 2, 3),
                          max_new_tokens=6, deadline_ms=60000.0), 0.0)
    da = a.select(1.0, max_batch=4, est=est)
    db = b.select(1.0, max_batch=4, est=est)
    assert [r.max_new_tokens for r in da.wave] \
        == [r.max_new_tokens for r in db.wave]
    assert da.degraded == db.degraded == {}
    with pytest.raises(ValueError, match="fraction"):
        QoSScheduler(incident_degrade=1.5)


# --- the busy-frac signal ---------------------------------------------------

def test_busy_frac_signal_watchable():
    rule = ThresholdRule(name="hot", signal="replica_busy_frac",
                         bound=0.99, op=">=", severity="warn")
    eng = _spawn_fifo("e", slots=2, max_len=64)
    mon = SLOMonitor([rule], source="e")
    sess = eng.session(slo=mon)
    for i in range(6):
        sess.submit(Request(rid=f"q{i}", arrival=0.0,
                            prompt=(1, 2, 3, 4), max_new_tokens=8))
    sess.advance_until(30.0)
    sess.finish()
    fired = [i for i in mon.incidents if i.rule == "hot"]
    assert fired, "saturated slots never tripped the busy-frac rule"
    # an idle engine never trips it
    eng2 = _spawn_fifo("e2", slots=2, max_len=64)
    mon2 = SLOMonitor([rule], source="e2")
    s2 = eng2.session(slo=mon2)
    s2.submit(Request(rid="one", arrival=0.0, prompt=(1, 2),
                      max_new_tokens=2))
    s2.advance_until(30.0)
    s2.finish()
    assert not [i for i in mon2.incidents if i.rule == "hot"]


# --- Incident.act -----------------------------------------------------------

def test_incident_act_closes_with_evidence():
    inc = _incident(t=3.0)
    inc.act(5.0, "join:s0")
    assert inc.t_close == 5.0
    assert inc.resolution == "action_taken"
    assert inc.evidence["action_taken"] == "join:s0"
    # idempotent: a second act (or act on a closed incident) is a noop
    inc.act(9.0, "drain:r0")
    assert inc.evidence["action_taken"] == "join:s0"
    d = inc.to_json()
    assert d["resolution"] == "action_taken"
    assert d["evidence"]["action_taken"] == "join:s0"


def test_count_oscillations():
    acts = [{"t": 10.0, "action": "join"},
            {"t": 50.0, "action": "drain"},
            {"t": 400.0, "action": "drain"}]
    assert count_oscillations(acts, 150.0) == 1
    assert count_oscillations(acts, 30.0) == 0
    assert count_oscillations([], 150.0) == 0


# --- the acceptance claim, small scale --------------------------------------

def test_autoscaled_beats_static_hours_holds_goodput():
    tr = synthesize_diurnal_trace(seed=0, n_requests=3000,
                                  service_tokens_per_unit=CAP6,
                                  peak_overload=1.25)
    auto = ClusterRouter(_spawn_qos, 2, placement="least_loaded",
                         slo=default_serving_rules(**RULES),
                         autoscale=_asc(standby=tuple(
                             f"s{i}" for i in range(6)),
                             min_replicas=2,
                             max_replicas=8)).run(tr)
    static = ClusterRouter(_spawn_qos, 6,
                           placement="least_loaded").run(tr)
    ra = auto.report(tenant_weights=WEIGHTS)
    rs = static.report(tenant_weights=WEIGHTS)
    assert ra["replica_hours"] < rs["replica_hours"]
    # the full >= 1.0 claim is gated at 10^5 bench scale; at 3k the
    # floor allows a small-sample haircut
    assert ra["goodput_tokens"] >= 0.95 * rs["goodput_tokens"]
    a = auto.autoscale
    assert a["joins"] >= 1 and a["drains"] >= 1
    assert count_oscillations(a["actions"], 150.0) == 0
    assert auto.census()["conserved"]


# --- bench gate family ------------------------------------------------------

def _gate(rows):
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/bench_gate.py"),
         "serving", "-"],
        input="\n".join(json.dumps(r) for r in rows),
        capture_output=True, text=True, cwd=REPO)
    out = [json.loads(ln) for ln in p.stdout.splitlines()
           if ln.startswith("{")]
    return p.returncode, out


def _as_row(kind, arm, **over):
    row = {"bench": "serving_autoscale", "trace_kind": kind,
           "arm": arm, "conserved": True, "pool_census_ok": True,
           "removal_census_ok": True, "goodput_tokens": 40000,
           "replica_hours": 12000.0}
    row.update(over)
    return row


def _as_summary(**over):
    row = {"bench": "serving_autoscale_summary",
           "hysteresis_window": 150.0, "requests": 100000,
           "static_replicas": 6,
           "action_log_deterministic": True, "off_identity": True}
    for kind in ("diurnal", "flash"):
        row[f"{kind}_goodput_ratio"] = 1.05
        row[f"{kind}_hours_ratio"] = 0.85
        row[f"{kind}_joins"] = 5
        row[f"{kind}_drains"] = 5
        row[f"{kind}_oscillations"] = 0
        row[f"{kind}_actions_taken"] = 2
    row.update(over)
    return row


def _as_rows(**sum_over):
    rows = [_as_row(k, a) for k in ("diurnal", "flash")
            for a in ("static_peak", "autoscaled")]
    rows.append(_as_summary(**sum_over))
    return rows


def test_bench_gate_serving_autoscale_family():
    rc, out = _gate(_as_rows())
    assert rc == 0 and out[-1]["gate"] == "pass"
    for bad, needle in (
            ({"diurnal_goodput_ratio": 0.97}, "reaction lag"),
            ({"flash_hours_ratio": 1.0}, "strictly below"),
            ({"flash_oscillations": 1}, "oscillation"),
            ({"diurnal_drains": 0}, "both directions"),
            ({"flash_actions_taken": 0}, "action_taken"),
            ({"action_log_deterministic": False}, "deterministic"),
            ({"off_identity": False}, "byte-identical")):
        rc, out = _gate(_as_rows(**bad))
        assert rc == 1, bad
        assert needle in out[-1]["reason"], (bad, out[-1])
    # broken census on any row fails before the summary is consulted
    rows = _as_rows()
    rows[1]["conserved"] = False
    rc, out = _gate(rows)
    assert rc == 1 and "census" in out[-1]["reason"]
    # a missing arm FAILs gracefully
    rc, out = _gate([_as_row("diurnal", "static_peak"),
                     _as_summary()])
    assert rc == 1 and "BOTH" in out[-1]["reason"]
    # no summary row: the claims are unverified
    rc, out = _gate([_as_row(k, a) for k in ("diurnal", "flash")
                     for a in ("static_peak", "autoscaled")])
    assert rc == 1 and "UNVERIFIED" in out[-1]["reason"]


# --- report tooling ---------------------------------------------------------

def test_trace_and_slo_reports_carry_action_timelines(tmp_path):
    trace_path = str(tmp_path / "as.json")
    res = ClusterRouter(_spawn_qos, 4, placement="least_loaded",
                        slo=default_serving_rules(**RULES),
                        autoscale=_asc(standby=("s0", "s1"),
                                       min_replicas=4,
                                       max_replicas=6),
                        trace=trace_path).run(_flash(n=2000))
    inc_path = str(tmp_path / "inc.jsonl")
    res.save_incidents(inc_path)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_report.py"),
         trace_path, "--json"],
        capture_output=True, text=True, cwd=REPO)
    rows = [json.loads(ln) for ln in p.stdout.splitlines()
            if ln.startswith("{")]
    arow = [r for r in rows if r["bench"] == "trace_report_autoscale"]
    assert arow and arow[0]["actions"] >= 1
    assert arow[0]["by_action"].get("join", 0) >= 1
    assert rows[-1]["bench"] == "trace_report"  # global row LAST
    q = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/slo_report.py"),
         inc_path, "--json"],
        capture_output=True, text=True, cwd=REPO)
    srows = [json.loads(ln) for ln in q.stdout.splitlines()
             if ln.startswith("{")]
    acts = [r for r in srows if r["bench"] == "slo_report_action"]
    assert acts and all(r["action"] for r in acts)
    assert srows[-1]["bench"] == "slo_report"
    assert srows[-1]["actions_taken"] == len(acts)


def test_reports_stay_byte_identical_without_autoscale(tmp_path):
    tr = synthesize_trace(seed=0, n_requests=10, vocab_size=509,
                          prompt_len=(4, 8), output_len=(2, 4))
    trace_path = str(tmp_path / "plain.json")
    ClusterRouter(_spawn_fifo, 2, trace=trace_path).run(tr)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_report.py"),
         trace_path, "--json"],
        capture_output=True, text=True, cwd=REPO)
    rows = [json.loads(ln) for ln in p.stdout.splitlines()
            if ln.startswith("{")]
    assert not [r for r in rows
                if r["bench"] == "trace_report_autoscale"]
    assert "autoscale" not in p.stdout.split("\n")[-2]
