"""End-to-end slice: LeNet on (synthetic) MNIST — BASELINE config 1.

~ the reference's test_mnist.py hapi test. Exercises the full stack:
DataLoader -> eager forward -> tape backward -> Adam step, plus the
jit'ed (to_static analog) training path used by bench.py.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.nn import functional as F
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_forward_shape():
    model = LeNet()
    x = paddle.randn([4, 1, 28, 28])
    out = model(x)
    assert out.shape == [4, 10]


def test_lenet_trains_eager():
    paddle.seed(0)
    model = LeNet()
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=model.parameters())
    train = MNIST(mode="train")
    # small slice for speed
    train.images = train.images[:512]
    train.labels = train.labels[:512]
    loader = DataLoader(train, batch_size=64, shuffle=True)

    first_loss = last_loss = None
    for epoch in range(3):
        for x, y in loader:
            logits = model(x)
            loss = F.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first_loss is None:
                first_loss = float(loss._value)
            last_loss = float(loss._value)
    assert last_loss < first_loss * 0.7, (first_loss, last_loss)

    # accuracy on train slice should be well above chance
    model.eval()
    correct = total = 0
    for x, y in DataLoader(train, batch_size=128):
        pred = model(x).numpy().argmax(-1)
        correct += (pred == y.numpy()).sum()
        total += len(pred)
    assert correct / total > 0.5


def test_lenet_trains_jit():
    """The perf path: functional jit'ed train step (to_static role)."""
    import jax
    import jax.numpy as jnp

    paddle.seed(0)
    model = LeNet()
    params = model.tree_flatten_params()

    def loss_fn(params, x, y):
        model.load_tree(params)
        with paddle.no_grad():
            pass
        logits = model(paddle.Tensor(x))
        loss = F.cross_entropy(logits, paddle.Tensor(y))
        return loss._value

    @jax.jit
    def train_step(params, x, y, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    train = MNIST(mode="train")
    xs = train.images[:256].astype(np.float32)[:, None] / 255.0
    ys = train.labels[:256]
    losses = []
    for i in range(20):
        j = (i * 64) % 256
        params, loss = train_step(params, jnp.asarray(xs[j:j + 64]),
                                  jnp.asarray(ys[j:j + 64]), 0.05)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_dataloader_workers():
    train = MNIST(mode="train")
    train.images = train.images[:200]
    train.labels = train.labels[:200]
    loader = DataLoader(train, batch_size=32, num_workers=2, shuffle=False)
    batches = list(loader)
    assert len(batches) == 7
    assert batches[0][0].shape == [32, 1, 28, 28]
    # order preserved vs sync loader
    sync = list(DataLoader(train, batch_size=32, num_workers=0))
    np.testing.assert_allclose(batches[0][0].numpy(), sync[0][0].numpy())
    np.testing.assert_allclose(batches[3][1].numpy(), sync[3][1].numpy())


def test_jit_to_static_layer():
    model = LeNet()
    model.eval()
    static_fn = paddle.jit.to_static(model.forward)
    x = paddle.randn([2, 1, 28, 28])
    out_static = static_fn(x)
    out_eager = model(x)
    np.testing.assert_allclose(out_static.numpy(), out_eager.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_jit_save_load(tmp_path):
    model = LeNet()
    model.eval()
    path = str(tmp_path / "lenet")
    paddle.jit.save(model, path,
                    input_spec=[paddle.jit.InputSpec([1, 1, 28, 28])])
    import os
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdiparams")
    loaded = paddle.jit.load(path)
    st = loaded.state_dict()
    assert "features.0.weight" in st
    # hlo text contains convolution op
    assert "convolution" in loaded._hlo_text
