"""paddle_tpu.serving.scheduler: the QoS front door — SLO-aware
admission, per-tenant weighted fair queueing, overload shedding,
graceful degradation, deadline timeouts — plus the engine integration
(deterministic fixed-clock replays), the overload acceptance claim
(qos goodput >= 1.15x fifo with tight-cohort SLO >= 0.9) and the
bench-gate contract for the serving_qos rows."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import (MetricsCollector, QoSScheduler, Request,
                                ServiceEstimator, ServingEngine,
                                synthesize_overload_trace, trace_stats)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# scheduler unit tests (no model, no engine)
# ---------------------------------------------------------------------------

def _req(rid, arrival=0.0, prompt=(1, 2, 3, 4), budget=8, **kw):
    return Request(rid=rid, arrival=arrival, prompt=tuple(prompt),
                   max_new_tokens=budget, **kw)


def _drain(sched, now=0.0, max_batch=1, est=None, chunk=1, n=100):
    """Serve the queue one admission at a time, committing each pick —
    the order a slots=1 engine would run."""
    est = est or ServiceEstimator()
    order = []
    for _ in range(n):
        if not sched.waiting():
            break
        dec = sched.select(now, max_batch=max_batch, est=est,
                           decode_chunk=chunk)
        assert dec.wave, (dec.shed, sched.queued_rids())
        r = dec.wave[0]
        sched.commit(r.rid)
        order.append(r.rid)
    return order


def test_wfq_weighted_service_order():
    """Two tenants, weight 2:1, equal-cost requests: the served stream
    interleaves ~2 A's per B instead of draining A first."""
    s = QoSScheduler(tenant_weights={"A": 2.0, "B": 1.0})
    for i in range(6):
        s.enqueue(_req(f"a{i}", tenant="A"), 0.0)
        s.enqueue(_req(f"b{i}", tenant="B"), 0.0)
    order = _drain(s)
    # after any prefix, A's served count stays ~2x B's (within one)
    a = b = 0
    for rid in order[:9]:
        a += rid.startswith("a")
        b += rid.startswith("b")
        assert a <= 2 * (b + 1) and b <= a // 2 + 1, order
    assert set(order) == {f"{t}{i}" for t in "ab" for i in range(6)}


def test_wfq_contains_aggressive_tenant():
    """A tenant flooding 3x the requests at equal weight still gets
    only ~half the early service — fair queueing, not FIFO."""
    s = QoSScheduler()
    for i in range(9):
        s.enqueue(_req(f"flood{i}", tenant="F"), 0.0)
    for i in range(3):
        s.enqueue(_req(f"meek{i}", tenant="M"), 0.0)
    first6 = _drain(s)[:6]
    assert sum(r.startswith("meek") for r in first6) == 3, first6


def test_strict_priority_above_wfq():
    """Priority classes trump tenant tags: every p1 request serves
    before any p0, regardless of tenant debt."""
    s = QoSScheduler(tenant_weights={"A": 100.0, "B": 1.0})
    for i in range(3):
        s.enqueue(_req(f"lo{i}", tenant="A", priority=0), 0.0)
        s.enqueue(_req(f"hi{i}", tenant="B", priority=1), 0.0)
    order = _drain(s)
    assert order[:3] == ["hi0", "hi1", "hi2"], order


def test_aging_prevents_priority_starvation():
    """With aging, a p0 request waiting long enough joins the p1 class
    and gets served ahead of fresher p1 traffic."""
    s = QoSScheduler(aging=10.0)
    s.enqueue(_req("old_lo", arrival=0.0, priority=0), 0.0)
    s.enqueue(_req("fresh_hi", arrival=29.0, priority=1), 29.0)
    dec = s.select(30.0, max_batch=1, est=ServiceEstimator())
    # old_lo aged +3 classes (30/10) > fresh_hi's static 1
    assert [r.rid for r in dec.wave] == ["old_lo"]


def test_deadline_infeasible_shed_at_admission():
    """A request whose deadline cannot be met even at the lowest
    degradation tier is shed at selection, never admitted."""
    s = QoSScheduler()
    # deadline 3 units out; even 2 tokens (tier 0.25 of 8) need
    # 1 prefill + 2 decode = 3 > 3 - already-elapsed margin... use 2.
    s.enqueue(_req("doomed", arrival=0.0, budget=8,
                   deadline_ms=2000.0), 0.0)
    s.enqueue(_req("fine", arrival=0.0, budget=4), 0.0)
    dec = s.select(0.0, max_batch=4, est=ServiceEstimator(),
                   decode_chunk=1)
    assert [r.rid for r in dec.wave] == ["fine"]
    assert len(dec.shed) == 1
    r, reason = dec.shed[0]
    assert r.rid == "doomed" and "infeasible" in reason
    assert s.waiting() == 1  # only "fine" remains queued


def test_degradation_tier_clamps_budget_before_shedding():
    """A deadline that fits half the budget admits the request CLAMPED
    (graceful degradation), not shed."""
    s = QoSScheduler(headroom=1.0)
    # budget 8: full needs 1 + 8 = 9 units; deadline 6 fits tier 0.5
    # (1 + 4 = 5 <= 6) but not 0.75 (1 + 6 = 7 > 6)
    s.enqueue(_req("clamp", arrival=0.0, budget=8,
                   deadline_ms=6000.0), 0.0)
    dec = s.select(0.0, max_batch=1, est=ServiceEstimator())
    assert len(dec.wave) == 1 and not dec.shed
    assert dec.wave[0].max_new_tokens == 4
    assert dec.degraded["clamp"] == (4, 8)


def test_custom_tiers_never_clamp_a_feasible_request():
    """degrade_tiers without 1.0 are FALLBACKS: a request whose full
    budget fits its deadline is admitted unclamped."""
    s = QoSScheduler(headroom=1.0, degrade_tiers=(0.75, 0.5))
    s.enqueue(_req("roomy", arrival=0.0, budget=10,
                   deadline_ms=100000.0), 0.0)
    dec = s.select(0.0, max_batch=1, est=ServiceEstimator())
    assert dec.wave[0].max_new_tokens == 10 and not dec.degraded
    # and the fallback still fires when full budget does NOT fit:
    # 1 + 10 = 11 > 9, but tier 0.75 -> 8 tokens, 1 + 8 = 9 <= 9
    s.enqueue(_req("squeezed", arrival=0.0, budget=10,
                   deadline_ms=9000.0), 0.0)
    s.commit("roomy")
    dec = s.select(0.0, max_batch=1, est=ServiceEstimator())
    assert dec.wave[0].max_new_tokens == 8
    assert dec.degraded["squeezed"] == (8, 10)


def test_commit_charges_the_degraded_budget():
    """A tenant served a clamped answer is charged for the clamp, not
    the original ask — otherwise degradation would also tax its
    future admission turns."""
    s = QoSScheduler()
    s.enqueue(_req("d", prompt=(1, 2, 3, 4), budget=8, tenant="T"),
              0.0)
    s.commit("d", budget=2)  # degraded 8 -> 2
    assert s._tags["T"] == pytest.approx((4 + 2) / 1.0)


def test_queue_bound_sheds_lowest_value_first():
    """Bounded queue: the victim is the lowest priority class, and
    within it the request least likely to meet its deadline."""
    s = QoSScheduler(max_queue=2)
    assert s.enqueue(_req("hi", priority=1), 0.0) == []
    assert s.enqueue(_req("lo_slack", priority=0,
                          deadline_ms=50000.0), 0.0) == []
    shed = s.enqueue(_req("lo_tight", priority=0, deadline_ms=5000.0),
                     0.0)
    assert len(shed) == 1
    assert shed[0][0].rid == "lo_tight"  # least slack among p0
    assert "queue bound" in shed[0][1]
    assert sorted(s.queued_rids()) == ["hi", "lo_slack"]


def test_shed_expired_drops_posthumous_requests():
    s = QoSScheduler()
    s.enqueue(_req("late", arrival=0.0, deadline_ms=1000.0), 0.0)
    s.enqueue(_req("alive", arrival=0.0, deadline_ms=100000.0), 0.0)
    out = s.shed_expired(5.0)
    assert [r.rid for r, _ in out] == ["late"]
    assert s.queued_rids() == ["alive"]


def test_scheduler_config_validation():
    with pytest.raises(ValueError, match="weight"):
        QoSScheduler(tenant_weights={"A": 0.0})
    with pytest.raises(ValueError, match="max_queue"):
        QoSScheduler(max_queue=0)
    with pytest.raises(ValueError, match="tiers"):
        QoSScheduler(degrade_tiers=(1.5,))
    with pytest.raises(ValueError, match="headroom"):
        QoSScheduler(headroom=0.5)
    with pytest.raises(ValueError, match="aging"):
        QoSScheduler(aging=0.0)


def test_estimator_ewma_tracks_observations():
    e = ServiceEstimator(prefill=1.0, decode=1.0, alpha=0.5)
    e.observe("decode", 3.0)
    assert e.decode == pytest.approx(2.0)
    e.observe("decode", 2.0)
    assert e.decode == pytest.approx(2.0)
    e.observe("prefill", -1.0)  # non-positive ignored
    assert e.prefill == 1.0
    with pytest.raises(ValueError, match="positive"):
        ServiceEstimator(prefill=0.0)


# ---------------------------------------------------------------------------
# QoS fields: JSONL round trip + trace generator
# ---------------------------------------------------------------------------

def test_request_qos_json_round_trip():
    r = Request(rid="x", arrival=1.5, prompt=(1, 2), max_new_tokens=4,
                tenant="gold", priority=2, deadline_ms=1500.0)
    assert Request.from_json(json.loads(json.dumps(r.to_json()))) == r
    # pre-QoS JSON (no new keys) loads with defaults — old traces
    # stay readable
    legacy = {"rid": "y", "arrival": 0.0, "prompt": [7],
              "max_new_tokens": 1}
    r2 = Request.from_json(legacy)
    assert (r2.tenant, r2.priority, r2.deadline_ms) == (None, 0, None)
    assert "tenant" not in r2.to_json()  # defaults stay off the wire
    assert r.deadline_time() == pytest.approx(3.0)
    assert r2.deadline_time() is None


def test_overload_trace_shape():
    """The generator delivers what it promises: 2x-capacity demand,
    one bursty aggressive tenant, tight/loose cohorts, determinism."""
    kw = dict(seed=3, n_requests=40, service_tokens_per_unit=4.0,
              overload=2.0, vocab_size=97)
    tr = synthesize_overload_trace(**kw)
    assert tr == synthesize_overload_trace(**kw)
    assert tr != synthesize_overload_trace(**{**kw, "seed": 4})
    assert len(tr) == 40
    arr = [r.arrival for r in tr]
    assert arr == sorted(arr)
    # demanded tokens / span == overload * service rate
    total = sum(r.max_new_tokens for r in tr)
    span = max(arr) - 0.0
    assert total / span == pytest.approx(8.0, rel=0.15)
    # every request carries a tenant, a priority and a deadline
    assert all(r.tenant in ("intl", "std", "bulk") for r in tr)
    assert all(r.deadline_ms is not None for r in tr)
    assert {r.priority for r in tr} == {0, 1}
    assert all(r.priority == 1 for r in tr if r.tenant == "intl")
    # the aggressive tenant arrives in simultaneous bursts of 4
    bulk_times = {}
    for r in tr:
        if r.tenant == "bulk":
            bulk_times.setdefault(r.arrival, 0)
            bulk_times[r.arrival] += 1
    assert max(bulk_times.values()) == 4
    # cohorts are named in the rid and consistent with the slack
    tight = [r for r in tr if r.rid.endswith(".tight")]
    loose = [r for r in tr if r.rid.endswith(".loose")]
    assert len(tight) + len(loose) == 40 and tight and loose
    for r in tight:
        assert r.deadline_ms == pytest.approx(
            (1 + r.max_new_tokens) * 1000.0 * 2.5)
    st = trace_stats(tr)
    assert st["tenants"] == ["bulk", "intl", "std"]
    assert st["deadline_requests"] == 40
    with pytest.raises(ValueError, match="tenant"):
        synthesize_overload_trace(tenants={})


# ---------------------------------------------------------------------------
# metrics: the QoS block
# ---------------------------------------------------------------------------

def test_metrics_qos_arithmetic():
    """Hand-built event stream -> exact shed/goodput/fairness numbers,
    and the invariant the gate checks: a shed request is never a hit."""
    m = MetricsCollector()
    # a: tenant A, met its 3s deadline, 3 tokens
    m.on_arrival("a", 0.0, tenant="A", deadline_ms=3000.0)
    m.on_admit("a", 0.5, "paged")
    m.on_tokens("a", 1.0, 3)
    m.on_finish("a", 2.0)
    # b: tenant B, missed its deadline, 4 tokens (no goodput)
    m.on_arrival("b", 0.0, tenant="B", deadline_ms=1000.0)
    m.on_admit("b", 0.5, "paged")
    m.on_tokens("b", 4.0, 4)
    m.on_finish("b", 5.0)
    # c: tenant B, shed — never admitted, never finished
    m.on_arrival("c", 1.0, tenant="B", priority=0, deadline_ms=500.0)
    m.on_shed("c", 1.0, "deadline-infeasible")
    # d: tenant A, timed out mid-decode (evicted), 2 tokens
    m.on_arrival("d", 0.0, tenant="A", deadline_ms=2000.0)
    m.on_admit("d", 0.5, "paged")
    m.on_tokens("d", 1.5, 2)
    m.on_finish("d", 4.0, evicted=True, reason="timeout")

    va = m.request("a")
    assert va["deadline_met"] is True and va["tenant"] == "A"
    assert m.request("b")["deadline_met"] is False
    vc = m.request("c")
    assert vc["shed"] and vc["deadline_met"] is False
    assert vc["finish"] is None and vc["finish_reason"] == "shed"
    vd = m.request("d")
    assert vd["deadline_met"] is False
    assert vd["evicted"] and vd["finish_reason"] == "timeout"

    rep = m.report(tenant_weights={"A": 1.0, "B": 1.0})
    assert rep["arrived"] == 4
    assert rep["completed"] == 3          # c shed, never completed
    assert rep["shed"] == 1 and rep["shed_rate"] == 0.25
    assert rep["deadline_requests"] == 3  # finished with deadlines
    assert rep["deadline_hits"] == 1      # only a
    assert rep["deadline_hits"] <= rep["completed"]
    assert rep["shed"] + rep["completed"] == rep["arrived"]
    assert rep["slo_deadline_attained"] == pytest.approx(1 / 3, abs=1e-4)
    assert rep["goodput_tokens"] == 3     # a only; b late, d timeout
    assert rep["timeout_evicted"] == 1
    # makespan 5.0 (first arrival 0 -> last finish 5)
    assert rep["goodput_tokens_per_sec"] == pytest.approx(0.6)
    t = rep["tenants"]
    assert t["A"]["goodput_tokens"] == 3 and t["B"]["goodput_tokens"] == 0
    assert t["B"]["shed"] == 1
    # Jain over [3, 0] = 9 / (2*9) = 0.5
    assert rep["fairness_jain"] == pytest.approx(0.5)


def test_deadline_free_evicted_request_is_not_goodput():
    """A canceled/timed-out stream without a deadline delivered
    partial work, not an SLO-met answer — its tokens must not inflate
    goodput (the metric the qos gate floors on)."""
    m = MetricsCollector()
    m.on_arrival("churn", 0.0, tenant="bulk")  # no deadline
    m.on_admit("churn", 0.5, "paged")
    m.on_tokens("churn", 1.0, 5)
    m.on_finish("churn", 2.0, evicted=True, reason="cancel")
    m.on_arrival("ok", 0.0, tenant="bulk")
    m.on_admit("ok", 0.5, "paged")
    m.on_tokens("ok", 1.0, 3)
    m.on_finish("ok", 4.0)
    assert m.request("churn")["deadline_met"] is False
    assert m.request("ok")["deadline_met"] is True
    rep = m.report()
    assert rep["goodput_tokens"] == 3


def test_plain_trace_report_has_no_qos_block():
    """No tenants, no deadlines, no sheds -> the PR-2 record, byte
    for byte (the default engine's determinism promise extends to the
    metrics schema)."""
    m = MetricsCollector()
    m.on_arrival("a", 0.0)
    m.on_admit("a", 0.5, "paged")
    m.on_tokens("a", 1.0, 2)
    m.on_finish("a", 2.0)
    rep = m.report()
    for k in ("arrived", "shed", "shed_rate", "goodput_tokens",
              "goodput_tokens_per_sec", "fairness_jain", "tenants",
              "degraded", "timeout_evicted"):
        assert k not in rep, k


# ---------------------------------------------------------------------------
# engine integration (tiny model, fixed-cost clock)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def srv_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    srv = llama_serving_decode_factory(model, max_len=48, page_size=8,
                                       n_pool_pages=25,
                                       batch_capacity=4,
                                       chunked_prefill=8)
    return srv


def _engine(srv, sched, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("policy", "paged")
    return ServingEngine(serving=srv, slots=4, scheduler=sched, **kw)


WEIGHTS = {"intl": 2.0, "std": 1.0, "bulk": 0.5}


def _overload_trace(seed=0, n=40):
    return synthesize_overload_trace(
        seed=seed, n_requests=n, service_tokens_per_unit=4.0,
        overload=2.0, prompt_len=(4, 12), output_len=(4, 12),
        vocab_size=97)


def test_engine_rejects_bogus_scheduler(srv_model):
    with pytest.raises(ValueError, match="scheduler"):
        ServingEngine(serving=srv_model, scheduler="lifo")


def test_scheduler_determinism(srv_model):
    """Same seeded trace + same scheduler config => identical
    completion order, slot log and metrics across two runs — the
    engine's determinism guarantee extended to the QoS path."""
    trace = _overload_trace()
    runs = []
    for _ in range(2):
        res = _engine(srv_model,
                      QoSScheduler(tenant_weights=WEIGHTS)).run(trace)
        order = sorted(res.outputs,
                       key=lambda rid: (
                           res.metrics.request(rid)["finish"], rid))
        runs.append((order, res.slot_log, res.shed,
                     res.report(tenant_weights=WEIGHTS)))
    assert runs[0] == runs[1]
    assert runs[0][2]  # overload actually shed something
    res = _engine(srv_model,
                  QoSScheduler(tenant_weights=WEIGHTS)).run(trace)
    assert res.scheduler == "qos"
    assert res.pages_free_end == res.pages_total  # sheds leak no pages


def test_qos_goodput_beats_fifo_on_overload(srv_model):
    """THE acceptance claim, in-tree: on the seeded 2x-overload
    multi-tenant trace (CPU, fixed-cost virtual clock) the QoS
    scheduler's goodput >= 1.15x FIFO's, with tight-cohort SLO
    attainment >= 0.9 — and shed requests are never counted as SLO
    hits."""
    trace = _overload_trace()
    rep_f = _engine(srv_model, None).run(trace) \
        .report(tenant_weights=WEIGHTS)
    res_q = _engine(srv_model,
                    QoSScheduler(tenant_weights=WEIGHTS)).run(trace)
    rep_q = res_q.report(tenant_weights=WEIGHTS)
    assert rep_q["goodput_tokens_per_sec"] >= \
        1.15 * rep_f["goodput_tokens_per_sec"], (rep_q, rep_f)
    hits = tot = 0
    for r in trace:
        if not r.rid.endswith(".tight"):
            continue
        v = res_q.metrics.request(r.rid)
        if v["shed"]:
            assert v["deadline_met"] is False  # shed is never a hit
            continue
        tot += 1
        hits += bool(v["deadline_met"])
    assert tot > 0 and hits / tot >= 0.9, (hits, tot)
    # the aggregate invariant the gate re-checks from the row
    assert rep_q["deadline_hits"] <= rep_q["completed"]
    assert rep_q["shed"] + rep_q["completed"] == rep_q["arrived"]
    # fairness: WFQ must not be WORSE than FIFO for the weighted mix
    assert rep_q["fairness_jain"] >= rep_f["fairness_jain"] - 1e-6


def test_no_starvation_under_saturating_high_priority(srv_model):
    """A high-priority tenant saturating capacity cannot starve the
    low-priority tenant when aging is on: every low request still
    completes (none shed, none starved past the run)."""
    rng = np.random.default_rng(17)
    trace = []
    for i in range(12):  # p1 flood: one arrival per time unit
        trace.append(Request(
            rid=f"hi{i:02d}", arrival=float(i),
            prompt=tuple(int(t) for t in rng.integers(1, 97, 6)),
            max_new_tokens=6, tenant="vip", priority=1))
    for i in range(3):   # p0 trickle arriving early
        trace.append(Request(
            rid=f"lo{i}", arrival=float(i),
            prompt=tuple(int(t) for t in rng.integers(1, 97, 6)),
            max_new_tokens=4, tenant="meek", priority=0))
    trace.sort(key=lambda r: (r.arrival, r.rid))
    res = _engine(srv_model, QoSScheduler(aging=8.0)).run(trace)
    assert not res.shed
    for i in range(3):
        assert len(res.outputs[f"lo{i}"]) == 4, i
    rep = res.report()
    assert rep["completed"] == 15


def test_deadline_timeout_unified_with_cancel_eviction(srv_model):
    """A running request whose deadline passes mid-decode is evicted
    through the cancel path: decode stops, pages free, metrics mark it
    evicted with reason 'timeout' — and the slot serves the next
    request."""
    rng = np.random.default_rng(23)
    mk = lambda rid, arrival, **kw: Request(
        rid=rid, arrival=arrival,
        prompt=tuple(int(t) for t in rng.integers(1, 97, 6)), **kw)
    # the honest trigger: admission says feasible when squeeze arrives
    # ALONE (headroom=1.0: 1 prefill + 10 decode ~ 11 <= 11.9), but
    # three later riders' prefills each steal a turn from squeeze's
    # decode stream as the second slot churns, so token 10 would land
    # past the deadline. The engine must evict at the first chunk past
    # 11.9 with 9 tokens, not burn the last chunk on a request
    # already lost.
    trace = [
        mk("squeeze", 0.0, max_new_tokens=10, deadline_ms=11900.0),
        mk("late0", 0.5, max_new_tokens=3),
        mk("late1", 0.5, max_new_tokens=3),
        mk("late2", 0.5, max_new_tokens=3),
    ]
    sched = QoSScheduler(headroom=1.0, degrade_tiers=())
    eng = ServingEngine(serving=srv_model, slots=2, scheduler=sched,
                        clock="fixed", policy="paged")
    res = eng.run(trace)
    v = res.metrics.request("squeeze")
    assert v["evicted"] and v["finish_reason"] == "timeout"
    assert v["n_tokens"] < 10        # stopped early
    assert v["deadline_met"] is False
    assert res.pages_free_end == res.pages_total
    for rid in ("late0", "late1", "late2"):
        assert len(res.outputs[rid]) == 3, rid


def test_dense_wave_honors_deadline_timeout(srv_model):
    """The timeout promise holds on the DENSE backend too: a wave
    member whose deadline passes while an earlier equal-length group
    monopolizes the chip stops streaming at the deadline and is marked
    evicted/timeout — dense handles it exactly like cancel_after
    (the batch computes on, the row takes no more tokens)."""
    rng = np.random.default_rng(41)
    pk = lambda n: tuple(int(t) for t in rng.integers(1, 97, n))
    trace = [
        # group S0=6 runs first: prefill + 11 decode units
        Request(rid="longrun", arrival=0.0, prompt=pk(6),
                max_new_tokens=12),
        # group S0=8 starts ~t=12 — past its 9-unit deadline, which
        # admission (pos 1: 2 prefills + 4 decode = 6 <= 9) could not
        # foresee because dense groups serialize
        Request(rid="misses", arrival=0.0, prompt=pk(8),
                max_new_tokens=4, deadline_ms=9000.0),
    ]
    sched = QoSScheduler(headroom=1.0)
    eng = ServingEngine(serving=srv_model, slots=4, scheduler=sched,
                        clock="fixed", policy="dense")
    res = eng.run(trace)
    v = res.metrics.request("misses")
    assert v["evicted"] and v["finish_reason"] == "timeout"
    assert v["n_tokens"] < 4 and v["deadline_met"] is False
    assert len(res.outputs["longrun"]) == 12
    # and the FIFO default on the same trace keeps PR-2 dense
    # semantics: no timeout, full budget streams late
    res_f = ServingEngine(serving=srv_model, slots=4, clock="fixed",
                          policy="dense").run(trace)
    vf = res_f.metrics.request("misses")
    assert not vf["evicted"] and vf["n_tokens"] == 4


def test_degraded_request_completes_within_deadline(srv_model):
    """End to end: a lone request whose deadline fits only half its
    budget is admitted clamped, streams the clamped count, and makes
    its SLO."""
    rng = np.random.default_rng(31)
    r = Request(rid="half", arrival=0.0,
                prompt=tuple(int(t) for t in rng.integers(1, 97, 6)),
                max_new_tokens=12, deadline_ms=8000.0)
    sched = QoSScheduler(headroom=1.0)
    res = _engine(srv_model, sched).run([r])
    v = res.metrics.request("half")
    assert v["degraded_from"] == 12
    assert len(res.outputs["half"]) < 12
    assert v["deadline_met"] is True
    assert not res.shed


def test_fifo_default_ignores_qos_fields(srv_model):
    """scheduler=None on a QoS trace: nothing is shed, nothing times
    out, everything completes FIFO — but the report still scores the
    deadlines (the baseline arm of the bench)."""
    trace = _overload_trace(n=12)
    res = _engine(srv_model, None).run(trace)
    assert res.scheduler == "fifo" and not res.shed
    rep = res.report()
    assert rep["completed"] == 12 and rep["shed"] == 0
    assert "slo_deadline_attained" in rep


# ---------------------------------------------------------------------------
# bench gate: the serving_qos family
# ---------------------------------------------------------------------------

def _run_gate(text, tmp_path):
    env = {**os.environ,
           "BENCH_GATE_SERVING_BASELINE": str(tmp_path / "b.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "serving", "-"], input=text, capture_output=True, text=True,
        timeout=60, cwd=REPO, env=env)
    return r.returncode, [json.loads(ln) for ln in
                          r.stdout.strip().splitlines()]


def _qos_row(sched, goodput, *, tight=1.0, hits=10, completed=20,
             shed=5, arrived=25):
    return json.dumps({
        "bench": "serving_qos", "scheduler": sched,
        "goodput_tokens_per_sec": goodput, "slo_tight_attained": tight,
        "tight_requests": 10, "deadline_hits": hits,
        "completed": completed, "shed": shed, "arrived": arrived,
        "shed_rate": round(shed / arrived, 4), "overload": 2.0,
        "device": "cpu"})


def test_bench_gate_serving_qos_family(tmp_path):
    # pass: 1.6x goodput, tight attained
    rc, recs = _run_gate("\n".join([
        _qos_row("fifo", 1.0), _qos_row("qos", 1.6)]) + "\n", tmp_path)
    assert rc == 0 and recs[-1]["gate"] == "pass"
    assert recs[-1]["qos_vs_fifo_goodput"] == pytest.approx(1.6)

    # sub-floor goodput FAILs naming the floor
    rc, recs = _run_gate("\n".join([
        _qos_row("fifo", 1.0), _qos_row("qos", 1.1)]) + "\n", tmp_path)
    assert rc == 1 and "1.15" in json.dumps(recs[-1])

    # tight-cohort attainment below 0.9 FAILs even with great goodput
    rc, recs = _run_gate("\n".join([
        _qos_row("fifo", 1.0), _qos_row("qos", 2.0, tight=0.5)]) + "\n",
        tmp_path)
    assert rc == 1 and "cohort" in recs[-1]["reason"]

    # a shed request counted as a hit breaks the aggregates -> FAIL
    rc, recs = _run_gate("\n".join([
        _qos_row("fifo", 1.0),
        _qos_row("qos", 2.0, hits=25, completed=20)]) + "\n", tmp_path)
    assert rc == 1 and "shed accounting" in recs[-1]["reason"]
    rc, recs = _run_gate("\n".join([
        _qos_row("fifo", 1.0),
        _qos_row("qos", 2.0, shed=0, completed=20, arrived=25)]) + "\n",
        tmp_path)
    assert rc == 1 and "shed accounting" in recs[-1]["reason"]

    # missing fifo row -> graceful FAIL, a record not a traceback
    rc, recs = _run_gate(_qos_row("qos", 2.0) + "\n", tmp_path)
    assert rc == 1 and "fifo" in recs[-1]["reason"]

    # qos family FAIL must not be masked by a passing workload family:
    # the last line carries the combined verdict
    wl = [json.dumps({"bench": "serving_workload", "policy": p,
                      "tokens_per_sec": t, "device": "cpu"})
          for p, t in (("routed", 100.0), ("paged", 90.0))]
    rc, recs = _run_gate("\n".join(wl + [
        _qos_row("fifo", 1.0), _qos_row("qos", 1.0)]) + "\n", tmp_path)
    assert rc == 1
    assert recs[-1]["combined"] is True
    assert recs[-1]["workload_gate"] == "pass"
    assert recs[-1]["qos_gate"] == "FAIL"
    assert recs[-1]["gate"] == "FAIL"
