"""LocalFS + HDFSClient (over a fake `hadoop` CLI shim).

~ reference python/paddle/fluid/tests/unittests/test_fs_interface.py and
hdfs tests: the reference exercises HDFSClient against a live hadoop CLI;
here a shell shim on PATH emulates `hadoop fs` over a local directory so
the exact command-line contract is tested hermetically.
"""
import os
import stat
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.distributed.fleet.utils.fs import (  # noqa: E402
    ExecuteError, FSFileExistsError, HDFSClient, LocalFS)

FAKE_HADOOP = r"""#!/bin/bash
# Minimal `hadoop fs` emulation over $FAKE_HDFS_ROOT.
shift  # drop "fs"
while [ "$1" = "-D" ]; do shift 2; done  # skip -D k=v config pairs
cmd="$1"; shift
root="${FAKE_HDFS_ROOT:?}"
p() { echo "$root/${1#/}"; }
case "$cmd" in
  -test)
    flag="$1"; path="$(p "$2")"
    case "$flag" in
      -d) [ -d "$path" ] ;;
      -e) [ -e "$path" ] ;;
      *) exit 2 ;;
    esac ;;
  -ls)
    path="$(p "$1")"
    [ -e "$path" ] || exit 1
    echo "Found $(ls "$path" | wc -l) items"
    for e in "$path"/*; do
      [ -e "$e" ] || continue
      if [ -d "$e" ]; then perm="drwxr-xr-x"; else perm="-rw-r--r--"; fi
      echo "$perm 1 u g 0 2026-01-01 00:00 $1/$(basename "$e")"
    done ;;
  -mkdir) shift; mkdir -p "$(p "$1")" ;;
  -put) src="$1"; cp "$src" "$(p "$2")" ;;
  -get) cp "$(p "$1")" "$2" ;;
  -mv) mv "$(p "$1")" "$(p "$2")" ;;
  -rm) shift; rm -rf "$(p "$1")" ;;
  -touchz) touch "$(p "$1")" ;;
  -cat) cat "$(p "$1")" ;;
  *) echo "unknown cmd $cmd" >&2; exit 1 ;;
esac
"""


@pytest.fixture
def fake_hadoop(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    sh = bindir / "hadoop"
    sh.write_text(FAKE_HADOOP)
    sh.chmod(sh.stat().st_mode | stat.S_IEXEC)
    hdfs_root = tmp_path / "hdfs"
    hdfs_root.mkdir()
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(hdfs_root))
    return hdfs_root


class TestLocalFS:
    def test_roundtrip(self, tmp_path):
        fs = LocalFS()
        d = tmp_path / "a" / "b"
        fs.mkdirs(str(d))
        assert fs.is_dir(str(d)) and fs.is_exist(str(d))
        f = d / "x.txt"
        f.write_text("hello")
        assert fs.is_file(str(f))
        assert fs.cat(str(f)) == "hello"
        dirs, files = fs.ls_dir(str(d))
        assert files == ["x.txt"] and dirs == []
        fs.mv(str(f), str(d / "y.txt"))
        assert fs.is_file(str(d / "y.txt"))
        with pytest.raises(FSFileExistsError):
            fs.touch(str(d / "y.txt"), exist_ok=False)
        fs.delete(str(d))
        assert not fs.is_exist(str(d))

    def test_mv_no_overwrite(self, tmp_path):
        fs = LocalFS()
        a, b = tmp_path / "a", tmp_path / "b"
        a.write_text("1")
        b.write_text("2")
        with pytest.raises(FSFileExistsError):
            fs.mv(str(a), str(b), overwrite=False)
        fs.mv(str(a), str(b), overwrite=True)
        assert b.read_text() == "1"


class TestHDFSClient:
    def test_roundtrip(self, fake_hadoop, tmp_path):
        fs = HDFSClient()
        assert fs.need_upload_download()
        fs.mkdirs("/ckpt/step1")
        assert fs.is_dir("/ckpt/step1")
        local = tmp_path / "w.bin"
        local.write_text("weights")
        fs.upload(str(local), "/ckpt/step1/w.bin")
        assert fs.is_file("/ckpt/step1/w.bin")
        assert fs.cat("/ckpt/step1/w.bin") == "weights"
        dirs, files = fs.ls_dir("/ckpt")
        assert dirs == ["step1"] and files == []
        _, files = fs.ls_dir("/ckpt/step1")
        assert files == ["w.bin"]
        out = tmp_path / "out.bin"
        fs.download("/ckpt/step1/w.bin", str(out))
        assert out.read_text() == "weights"
        fs.mv("/ckpt/step1", "/ckpt/step2")
        assert fs.is_dir("/ckpt/step2") and not fs.is_exist("/ckpt/step1")
        fs.touch("/ckpt/DONE")
        assert fs.is_file("/ckpt/DONE")
        fs.delete("/ckpt")
        assert not fs.is_exist("/ckpt")

    def test_missing_binary(self, monkeypatch, tmp_path):
        fs = HDFSClient(hadoop_home=str(tmp_path / "nope"))
        with pytest.raises(ExecuteError):
            fs.mkdirs("/x")

    def test_hadoop_home_and_configs(self, fake_hadoop, tmp_path):
        # hadoop_home path resolution: link the shim under home/bin
        home = tmp_path / "hh"
        (home / "bin").mkdir(parents=True)
        shim = subprocess.run(["which", "hadoop"], capture_output=True,
                              text=True).stdout.strip()
        os.symlink(shim, home / "bin" / "hadoop")
        fs = HDFSClient(hadoop_home=str(home),
                        configs={"fs.default.name": "hdfs://x:9000"})
        fs.mkdirs("/via_home")
        assert fs.is_dir("/via_home")
