"""obs.slo units (PR 9): rule validation, streaming threshold /
multi-window burn-rate / heartbeat-silence evaluation, incident
lifecycle (open/close/re-arm, deterministic ids), the IncidentLog
JSONL round-trip with the shared torn-tail tolerance, the event
auto-open path, the QoSScheduler subscription seam, and the
``percentile`` satellite (one public helper, defined small-n
semantics)."""
import json

import numpy as np
import pytest

from paddle_tpu.obs.slo import (BurnRateRule, HeartbeatRule, Incident,
                                IncidentLog, SLOMonitor,
                                ThresholdRule, default_serving_rules,
                                load_incidents)
from paddle_tpu.serving.metrics import percentile
from paddle_tpu.serving.scheduler import QoSScheduler


def _view(rid="a", *, met=True, shed=False, reason=None, ttft=None,
          tpot=None):
    return {"rid": rid, "deadline_met": met, "shed": shed,
            "finish_reason": "shed" if shed else reason,
            "ttft": ttft, "tpot": tpot}


# --- rule validation --------------------------------------------------------

def test_rule_validation():
    with pytest.raises(ValueError, match="op"):
        ThresholdRule(name="t", signal="q", bound=1.0, op="==")
    with pytest.raises(ValueError, match="severity"):
        ThresholdRule(name="t", signal="q", bound=1.0,
                      severity="panic")
    with pytest.raises(ValueError, match="objective"):
        BurnRateRule(name="b", objective=1.0)
    with pytest.raises(ValueError, match="bad="):
        BurnRateRule(name="b", objective=0.9, bad="explosions")
    with pytest.raises(ValueError, match="window"):
        BurnRateRule(name="b", objective=0.9, windows=((0.0, 1.0),))
    with pytest.raises(ValueError, match="timeout"):
        HeartbeatRule(name="h", timeout=0.0)
    with pytest.raises(ValueError, match="unique"):
        SLOMonitor([HeartbeatRule(name="x", timeout=1.0),
                    HeartbeatRule(name="x", timeout=2.0)])
    with pytest.raises(ValueError, match="rule type"):
        SLOMonitor([object()])
    assert BurnRateRule(name="b", objective=0.9).budget \
        == pytest.approx(0.1)


# --- threshold rules --------------------------------------------------------

def test_threshold_fires_recovers_and_rearms():
    mon = SLOMonitor([ThresholdRule(name="deep", signal="queue_depth",
                                    bound=5.0)])
    mon.observe_value("queue_depth", 3, 1.0)
    assert len(mon.log) == 0
    mon.observe_value("queue_depth", 7, 2.0)
    assert len(mon.log) == 1
    inc = mon.log.incidents[0]
    assert inc.kind == "threshold" and inc.open
    assert inc.evidence["value"] == 7
    # still breached: the OPEN incident absorbs it (no re-fire)
    mon.observe_value("queue_depth", 9, 3.0)
    assert len(mon.log) == 1
    # recovery closes; the next breach is a NEW incident
    mon.observe_value("queue_depth", 2, 4.0)
    assert not inc.open and inc.resolution == "recovered"
    mon.observe_value("queue_depth", 8, 5.0)
    assert len(mon.log) == 2


def test_threshold_sustained_for_units():
    mon = SLOMonitor([ThresholdRule(name="deep", signal="queue_depth",
                                    bound=5.0, for_units=3.0)])
    mon.observe_value("queue_depth", 7, 1.0)
    assert len(mon.log) == 0          # breached, not yet sustained
    mon.advance(2.0)
    assert len(mon.log) == 0
    mon.advance(4.0)                  # 3 units after breach start
    assert len(mon.log) == 1
    assert mon.log.incidents[0].evidence["breach_since"] == 1.0
    # a dip resets the episode clock
    mon2 = SLOMonitor([ThresholdRule(name="deep",
                                     signal="queue_depth",
                                     bound=5.0, for_units=3.0)])
    mon2.observe_value("queue_depth", 7, 1.0)
    mon2.observe_value("queue_depth", 1, 2.0)
    mon2.observe_value("queue_depth", 7, 2.5)
    mon2.advance(4.0)
    assert len(mon2.log) == 0         # only 1.5 units sustained


def test_threshold_sustained_breach_ending_at_next_sample():
    # the breach's END is the first evaluation point (no unrelated
    # traffic advanced the clock mid-episode): a 10-unit breach with
    # for_units=5 must STILL fire — retroactively, at the recovery
    # sample — and close there
    mon = SLOMonitor([ThresholdRule(name="deep", signal="queue_depth",
                                    bound=64.0, for_units=5.0)])
    mon.observe_value("queue_depth", 80, 0.0)
    mon.observe_value("queue_depth", 10, 10.0)
    assert len(mon.log) == 1
    inc = mon.log.incidents[0]
    assert not inc.open and inc.resolution == "recovered"
    assert inc.t_open == inc.t_close == 10.0
    assert inc.evidence["value"] == 80.0       # the breaching value
    assert inc.evidence["breach_since"] == 0.0
    # a SHORT episode ending at the next sample stays silent
    mon2 = SLOMonitor([ThresholdRule(name="deep",
                                     signal="queue_depth",
                                     bound=64.0, for_units=5.0)])
    mon2.observe_value("queue_depth", 80, 0.0)
    mon2.observe_value("queue_depth", 10, 2.0)
    assert len(mon2.log) == 0


def test_threshold_on_request_field():
    mon = SLOMonitor([ThresholdRule(name="slow_ttft", signal="ttft",
                                    bound=10.0)])
    mon.observe_request(_view("r1", ttft=2.0), 1.0)
    assert len(mon.log) == 0
    mon.observe_request(_view("r2", ttft=30.0), 2.0)
    assert len(mon.log) == 1
    assert mon.log.incidents[0].rids == ["r2"]


# --- burn-rate rules --------------------------------------------------------

def _burn_rule(**kw):
    kw.setdefault("name", "burn")
    kw.setdefault("objective", 0.9)      # 10% error budget
    kw.setdefault("windows", ((10.0, 5.0), (4.0, 5.0)))
    kw.setdefault("min_events", 4)
    return BurnRateRule(**kw)


def test_burn_rate_fires_only_when_all_windows_burn():
    mon = SLOMonitor([_burn_rule()])
    # 4 bad of 4 in both windows: burn = 1.0/0.1 = 10 >= 5 -> fire
    for i in range(4):
        mon.observe_request(_view(f"r{i}", met=False), 1.0 + i)
    assert len(mon.log) == 1
    inc = mon.log.incidents[0]
    assert inc.kind == "burn_rate" and inc.severity == "page"
    wins = inc.evidence["windows"]
    assert all(w["burn"] >= w["threshold"] for w in wins)
    assert inc.rids == [f"r{i}" for i in range(4)]


def test_burn_rate_respects_min_events_and_short_window():
    mon = SLOMonitor([_burn_rule()])
    # 3 bad: below min_events, silent no matter how bad the rate
    for i in range(3):
        mon.observe_request(_view(f"r{i}", met=False), 1.0 + i)
    assert len(mon.log) == 0
    # an OLD error storm outside the short window must not fire:
    # 4 bad at t~1-2, then good traffic; at t=20 the short window
    # (4 units) holds only good events
    mon2 = SLOMonitor([_burn_rule()])
    for i in range(4):
        mon2.observe_request(_view(f"b{i}", met=False), 1.0 + 0.2 * i)
    # already fired at t~1.6 (both windows bad); close it via recovery
    for i in range(8):
        mon2.observe_request(_view(f"g{i}", met=True), 17.0 + 0.2 * i)
    assert len(mon2.log) == 1
    assert not mon2.log.incidents[0].open
    assert mon2.log.incidents[0].resolution == "burn_recovered"


def test_burn_rate_shed_predicate_and_budget_evidence():
    mon = SLOMonitor([_burn_rule(bad="shed", severity="warn")])
    for i in range(2):
        mon.observe_request(_view(f"ok{i}", met=True), 1.0 + i)
    for i in range(6):
        mon.observe_request(_view(f"s{i}", shed=True, met=False),
                            3.0 + 0.1 * i)
    # fires at the SECOND shed (4 events, 2 bad: burn 5.0 crosses the
    # threshold with min_events met) and stays one open incident no
    # matter how many more sheds pile on
    assert len(mon.log) == 1
    ev = mon.log.incidents[0].evidence
    assert ev["cum_events"] == 4 and ev["cum_bad"] == 2
    # budget_spent = cum_bad / (cum_events * (1 - objective))
    assert ev["budget_spent"] == pytest.approx(
        ev["cum_bad"] / (ev["cum_events"] * 0.1))


def test_burn_rate_rids_exclude_recovered_bursts():
    # a brief bad burst that recovers must not pollute a much later
    # incident's offending-rid list (the postmortem pointer)
    mon = SLOMonitor([_burn_rule()])
    mon.observe_request(_view("old0", met=False), 1.0)
    for i in range(20):
        mon.observe_request(_view(f"good{i}", met=True), 2.0 + i)
    assert len(mon.log) == 0          # 1-of-N never burns enough
    for i in range(8):
        mon.observe_request(_view(f"new{i}", met=False),
                            100.0 + 0.1 * i)
    assert len(mon.log) == 1
    rids = mon.log.incidents[0].rids
    assert rids and all(r.startswith("new") for r in rids)


def test_monitor_reset_starts_a_fresh_session():
    mon = SLOMonitor([_burn_rule()])
    for i in range(4):
        mon.observe_request(_view(f"a{i}", met=False), 1000.0 + i)
    assert len(mon.log) == 1
    mon.reset()
    assert len(mon.log) == 0 and mon.t == 0.0
    # a SECOND replay's low timestamps evaluate from scratch — the
    # previous run's advanced clock must not blind the windows
    for i in range(4):
        mon.observe_request(_view(f"b{i}", met=False), 1.0 + i)
    assert len(mon.log) == 1
    assert mon.log.incidents[0].rids == [f"b{i}" for i in range(4)]


# --- heartbeat silence ------------------------------------------------------

def test_heartbeat_silence_fires_once_and_resumes():
    mon = SLOMonitor([HeartbeatRule(name="hb", timeout=5.0)],
                     source="r0")
    mon.heartbeat(1.0)
    mon.advance(5.9)
    assert len(mon.log) == 0
    mon.advance(6.0)                  # silent for 5.0
    assert len(mon.log) == 1
    inc = mon.log.incidents[0]
    assert inc.kind == "heartbeat_silence" and inc.source == "r0"
    mon.advance(8.0)                  # still silent: same incident
    assert len(mon.log) == 1 and inc.open
    mon.heartbeat(9.0)                # back: closes + re-arms
    assert not inc.open and inc.resolution == "heartbeat_resumed"
    mon.advance(14.5)
    assert len(mon.log) == 2


def test_any_signal_counts_as_liveness():
    # a replica emitting metrics is alive even if nobody probes it
    mon = SLOMonitor([HeartbeatRule(name="hb", timeout=5.0)])
    mon.observe_value("queue_depth", 1, 4.0)
    mon.observe_request(_view("a"), 8.0)
    mon.advance(12.0)
    assert len(mon.log) == 0          # never 5 silent units


# --- events, retirement, callbacks ------------------------------------------

def test_event_auto_open_close_and_close_kind():
    mon = SLOMonitor([], source="r1")
    stall = mon.event("stall", 2.0, severity="warn", close_t=6.0,
                      evidence={"duration": 4.0})
    crash = mon.event("crash", 3.0)
    point = mon.event("decode_error", 4.0, severity="warn",
                      close_t=4.0, rids=["x"])
    assert point is not None and not point.open
    assert stall.open and crash.open
    mon.advance(6.0)                  # the stall's scheduled close
    assert not stall.open and stall.resolution == "event_complete"
    assert mon.close_kind("crash", 7.0, "failover") == 1
    assert crash.resolution == "failover"
    with pytest.raises(ValueError, match="severity"):
        mon.event("crash", 1.0, severity="meh")


def test_retire_closes_and_silences():
    mon = SLOMonitor([HeartbeatRule(name="hb", timeout=2.0)],
                     source="r0")
    inc = mon.event("crash", 1.0)
    mon.retire(2.0, resolution="failover")
    assert not inc.open and inc.resolution == "failover"
    # a retired monitor evaluates nothing and opens nothing
    mon.advance(99.0)
    assert mon.event("crash", 100.0) is None
    mon.observe_value("queue_depth", 50, 101.0)
    assert len(mon.log) == 1


def test_incident_ids_deterministic_and_shared_log():
    log = IncidentLog()
    a = SLOMonitor([], source="r0", log=log)
    b = SLOMonitor([], source="r1", log=log)
    a.event("crash", 1.0)
    b.event("stall", 2.0, severity="warn", close_t=3.0)
    a.event("failover", 4.0, close_t=4.0)
    assert [i.id for i in log] == ["inc-0000", "inc-0001", "inc-0002"]
    assert log.by_kind() == {"crash": 1, "failover": 1, "stall": 1}


def test_qos_scheduler_subscription_seam():
    sched = QoSScheduler()
    mon = SLOMonitor([], source="r0",
                     on_incident=[sched.note_incident])
    mon.event("crash", 1.0)
    mon.event("stall", 2.0, severity="warn", close_t=3.0)
    assert [i.kind for i in sched.incidents_seen] == ["crash",
                                                      "stall"]
    # detect-and-report only: a noted incident changes NO admission
    # arithmetic (reset leaves the history in place, queue untouched)
    sched.reset()
    assert len(sched.incidents_seen) == 2
    assert sched.waiting() == 0
    # late subscription works too
    seen = []
    mon.subscribe(seen.append)
    mon.event("decode_error", 4.0, severity="warn", close_t=4.0)
    assert len(seen) == 1


# --- persistence (satellite: tolerant JSONL) --------------------------------

def test_incident_log_roundtrip_and_torn_tail(tmp_path):
    log = IncidentLog()
    mon = SLOMonitor([_burn_rule()], source="r0", log=log)
    for i in range(4):
        mon.observe_request(_view(f"r{i}", met=False), 1.0 + i)
    mon.event("crash", 9.0)
    # parents are created (framework/io.py save discipline): dumping
    # into a fresh output tree must not crash after a long replay
    path = str(tmp_path / "fresh" / "tree" / "incidents.jsonl")
    log.save(path)
    back = load_incidents(path)
    assert [i.to_json() for i in back] \
        == [i.to_json() for i in log]
    assert isinstance(back[0], Incident)
    # torn FINAL line: warn + valid prefix (the crash-written file)
    with open(path) as f:
        lines = f.read().splitlines(True)
    with open(path, "w") as f:
        f.writelines(lines[:-1])
        f.write(lines[-1][: len(lines[-1]) // 2])
    with pytest.warns(UserWarning, match="truncated"):
        assert len(load_incidents(path)) == len(log) - 1
    # a MID-file tear is not a torn tail: loud error
    with open(path, "w") as f:
        f.write('{"broken\n')
        f.writelines(lines[1:])
    with pytest.raises(ValueError, match="malformed"):
        load_incidents(path)


def test_default_serving_rules_shape():
    rules = default_serving_rules(queue_bound=64)
    kinds = sorted(type(r).__name__ for r in rules)
    assert kinds == ["BurnRateRule", "BurnRateRule", "ThresholdRule"]
    # the stock set is monitor-constructible as-is
    SLOMonitor(rules)


# --- percentile satellite ---------------------------------------------------

def test_percentile_small_n_semantics():
    assert percentile([], 50) is None
    assert percentile(None, 95) is None
    # n == 1: the value, for every q
    assert percentile([7.0], 50) == 7.0
    assert percentile([7.0], 95) == 7.0
    # n == 2: linear interpolation between the two
    assert percentile([0.0, 10.0], 50) == 5.0
    assert percentile([0.0, 10.0], 95) == 9.5
    # matches numpy on larger samples (the report paths' arithmetic)
    xs = [3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0]
    assert percentile(xs, 95) == pytest.approx(
        round(float(np.percentile(np.asarray(xs), 95)), 6))


def test_percentile_is_the_report_arithmetic():
    # the collector's report percentiles go through the same helper
    from paddle_tpu.serving.metrics import MetricsCollector
    m = MetricsCollector()
    m.on_arrival("a", 0.0)
    m.on_admit("a", 1.0, "paged")
    m.on_tokens("a", 2.0, 1)
    m.on_finish("a", 3.0)
    rec = m.report()
    assert rec["ttft_p50"] == percentile([2.0], 50)
    assert rec["e2e_p95"] == percentile([3.0], 95)
