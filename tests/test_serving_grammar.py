"""Constrained decoding: grammar/JSON-schema guided generation.

The claims: JSON schemas and a small EBNF subset compile host-side to
token-level DFAs whose packed allow-bitmasks decode through ONE
fixed-shape compiled batch (the mask bank + per-row flat state ids are
jit data — the ``decode_n`` program cache stays flat across schema
churn), every constrained stream detokenizes to text its schema
validates and stops at the automaton's accept, free rows riding the
same batch are token-identical to an unconstrained engine,
``grammar=None`` everywhere is byte-identical to the pre-grammar
engine (outputs, slot logs, decisions, metrics records, report keys,
registry contents), the budgeted ``GrammarCache`` honors LRU
retention / pin-while-in-flight / refusal-requeues with its
resident+evictable+free census conserved, constrained rows compose
with LoRA (``adapter_schemas`` defaults) / TP / QoS degrade (the
min-tokens floor) / disaggregated handoffs / host-DRAM preemption,
``Request.schema`` round-trips JSONL with legacy traces untouched,
the metrics/trace grammar blocks appear ONLY for constrained traffic,
and the ``serving_grammar`` bench-gate family passes its pass rows
and fails its FAIL rows.
"""
import dataclasses
import json
import os
import sys

import pytest

import paddle_tpu as paddle
from paddle_tpu.models.nlp.llama_decode import (
    GrammarConfig, as_grammar_config, grammar_bank_hooks)
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.serving import (AdapterStore, ClusterRouter,
                                GrammarCache, GrammarStore,
                                QoSScheduler, Request, ServingEngine,
                                TokenVocab, compile_grammar,
                                compile_schema, compile_source,
                                load_trace, make_sim_serving,
                                save_trace, schema_accepts,
                                synthesize_schema_trace,
                                synthesize_trace, trace_stats)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 97          # the real tiny-llama vocab (>= ascii_default's 97)
SIMVOCAB = 509
COSTS = {"prefill_unit": 1.0, "decode": 1.0, "grammar_compile": 1.0}
VO = TokenVocab.ascii_default(SIMVOCAB)

# one required property per schema, the KEY baked per schema id — two
# schemas never accept the same text (the bench arm's palette)
_KINDS = [{"type": "boolean"},
          {"type": "integer", "maxDigits": 3},
          {"enum": ["lo", "mid", "hi"]},
          {"type": "string", "maxLength": 6}]


def _schemas(n=4):
    return {f"s{k}": {"type": "object",
                      "properties": {f"k{k}": _KINDS[k % len(_KINDS)]},
                      "required": [f"k{k}"]}
            for k in range(n)}


def _store(n=4):
    return GrammarStore(_schemas(n))


def _sim_engine(grammar_slots=None, grammar=None, slots=8, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("fixed_costs", dict(COSTS))
    kw.setdefault("decode_chunk", 4)
    return ServingEngine(
        serving=make_sim_serving(max_len=96, page_size=8, slots=slots,
                                 vocab=SIMVOCAB,
                                 grammar_slots=grammar_slots),
        slots=slots, policy="paged", grammar=grammar, **kw)


def _trace(seed=0, n=40, n_schemas=4, **kw):
    kw.setdefault("overload", 0.6)     # sub-saturation: no evictions
    return synthesize_schema_trace(seed=seed, n_requests=n,
                                   n_schemas=n_schemas,
                                   vocab_size=SIMVOCAB, **kw)


# --- Request.schema + trace round-trip --------------------------------------

def test_request_schema_roundtrip(tmp_path):
    """The schema field survives JSONL; the key is written only when
    set, so schema-less records are byte-identical to PR 17's."""
    r = Request(rid="x", arrival=1.0, prompt=(1, 2), max_new_tokens=3,
                schema="invoice")
    assert Request.from_json(r.to_json()) == r
    plain = Request(rid="y", arrival=2.0, prompt=(3,), max_new_tokens=1)
    assert "schema" not in plain.to_json()
    assert Request.from_json(plain.to_json()).schema is None
    p = tmp_path / "t.jsonl"
    save_trace(str(p), [r, plain])
    assert load_trace(str(p)) == [r, plain]


def test_legacy_trace_jsonl_no_schema_key(tmp_path):
    """A schema-less trace's JSONL carries no ``schema`` key — the
    byte-identity regression against the pre-grammar serializer."""
    trace = synthesize_trace(seed=3, n_requests=6, vocab_size=VOCAB)
    p = tmp_path / "t.jsonl"
    save_trace(str(p), trace)
    for line in open(p):
        assert "schema" not in json.loads(line)


def test_schema_trace_shape():
    """Seeded determinism, rid-baked schema ids, Zipf head heavier
    than tail, loose deadlines, trace_stats keys, JSONL round-trip."""
    a = _trace(seed=7, n=400)
    b = _trace(seed=7, n=400)
    assert a == b
    assert any(r.schema is None and r.rid.endswith(".free") for r in a)
    counts = {}
    for r in a:
        if r.schema is not None:
            assert r.rid.endswith("." + r.schema)
            counts[r.schema] = counts.get(r.schema, 0) + 1
    assert counts["s0"] > counts["s3"]  # the Zipf skew
    assert all(r.deadline_ms is not None for r in a)
    st = trace_stats(a)
    assert st["schemas"] == sorted(counts)
    assert st["schema_requests"] == sum(counts.values())
    # schema-less stats carry no schema keys
    st0 = trace_stats(synthesize_trace(seed=0, n_requests=4))
    assert "schemas" not in st0 and "schema_requests" not in st0
    with pytest.raises(ValueError, match="schema"):
        synthesize_schema_trace(n_schemas=0)


# --- the compiler ------------------------------------------------------------

def test_token_vocab():
    with pytest.raises(ValueError, match="97"):
        TokenVocab.ascii_default(96)
    v = TokenVocab.ascii_default(97)
    text = '{"k": [1, "ab"]}'
    assert v.decode(v.encode(text)) == text
    assert v.surface(1) == " " and v.surface(96) is None
    with pytest.raises(ValueError, match="no token"):
        v.encode("é")
    with pytest.raises(ValueError, match="vocab_size"):
        TokenVocab({1: "a"}, 1)
    with pytest.raises(ValueError, match="outside"):
        TokenVocab({0: "a"}, 4)


@pytest.mark.parametrize("schema,good,bad", [
    ({"type": "boolean"}, "true", "yes"),
    ({"type": "null"}, "null", "nil"),
    ({"type": "integer", "maxDigits": 3}, "-42", "1234"),
    ({"type": "integer", "maxDigits": 2, "minimum": 0}, "7", "-7"),
    ({"enum": ["lo", "mid", "hi"]}, '"mid"', '"md"'),
    ({"type": "string", "minLength": 2, "maxLength": 4},
     '"abcd"', '"a"'),
    ({"type": "array", "items": {"type": "boolean"}, "minItems": 1,
      "maxItems": 2}, "[true,false]", "[true,false,true]"),
    ({"type": "object",
      "properties": {"ok": {"type": "boolean"},
                     "n": {"type": "integer", "maxDigits": 2}},
      "required": ["ok", "n"]},
     '{"ok":true,"n":12}', '{"ok":1,"n":12}'),
])
def test_compile_schema_accepts_exactly(schema, good, bad):
    """Each schema kind compiles to a DFA that accepts precisely the
    strings ``schema_accepts`` validates: a valid serialization walks
    to an accepting state, an invalid one is rejected (a forbidden
    token or a non-accepting end state)."""
    g = compile_schema(schema, VO)
    st = g.walk(VO.encode(good))
    assert g.accepts_at(st)
    assert schema_accepts(schema, good)
    assert not schema_accepts(schema, bad)
    try:
        st = g.walk(VO.encode(bad))
        assert not g.accepts_at(st)
    except ValueError:
        pass  # rejected mid-walk: a token the mask forbids
    if schema.get("type") == "object":
        # the DFA emits properties in declaration order — a reordered
        # (but semantically valid) serialization is NOT in the
        # generated language
        with pytest.raises(ValueError):
            g.walk(VO.encode('{"n":12,"ok":true}'))
    assert 1 <= g.min_tokens <= len(good)
    # masks and trans can never disagree: every allowed bit has a
    # transition and vice versa
    from paddle_tpu.serving.grammar import unpack_row
    for s in range(1, g.n_states):
        allow = unpack_row(g.masks[s], g.vocab_size)
        assert (allow == (g.trans[s] >= 0)).all()
        frac = g.masked_frac(s)
        assert 0.0 <= frac <= 1.0
        if allow.any():
            assert frac < 1.0


def test_compile_ebnf_and_source_dispatch():
    g = compile_grammar('root ::= "ab" | "c" d\nd ::= [0-9]{1,2}', VO)
    for text in ("ab", "c7", "c07"):
        assert g.accepts_at(g.walk(VO.encode(text)))
    assert not g.accepts_at(g.walk(VO.encode("c")))
    assert g.min_tokens == 2 and g.max_tokens == 3
    # unbounded repetition -> cyclic DFA, max_tokens None
    cyc = compile_grammar("root ::= [ab]+", VO)
    assert cyc.max_tokens is None
    with pytest.raises(ValueError, match="unknown rule"):
        compile_grammar('root ::= miss', VO)
    with pytest.raises(ValueError, match="recursive|expands"):
        compile_grammar('root ::= "a" root', VO)
    with pytest.raises(ValueError, match="::="):
        compile_grammar("root = 'a'", VO)
    # compile_source dispatches on the source type
    assert compile_source({"type": "boolean"}, VO).accepts_at(
        compile_source({"type": "boolean"}, VO).walk(VO.encode("true")))
    assert compile_source('root ::= "x"', VO).min_tokens == 1
    with pytest.raises(ValueError, match="schema dict or EBNF"):
        compile_source(42, VO)
    # a grammar whose alphabet is outside the vocab accepts nothing
    tiny = TokenVocab({1: "a"}, 4)
    with pytest.raises(ValueError, match="no token"):
        compile_grammar('root ::= "b"', tiny)
    # ...and one whose start allows tokens but can never reach an
    # accepting state is refused at compile too
    with pytest.raises(ValueError, match="accepts no string"):
        compile_grammar('root ::= "a" "b"', tiny)


def test_pack_unpack_roundtrip():
    import numpy as np
    from paddle_tpu.serving.grammar import pack_masks, unpack_row
    rng = np.random.default_rng(0)
    allow = rng.random((5, 77)) < 0.3
    packed = pack_masks(allow)
    assert packed.dtype == np.uint32
    for s in range(5):
        assert (unpack_row(packed[s], 77) == allow[s]).all()


# --- GrammarCache units ------------------------------------------------------

def _gcache(n_slots=3, n=6, max_states=64):
    store = _store(n)
    sim = make_sim_serving(grammar_slots=n_slots,
                           grammar_states=max_states, vocab=SIMVOCAB)
    return store, GrammarCache(store, n_slots, max_states,
                               TokenVocab.ascii_default(SIMVOCAB),
                               sim.init_grammar_bank,
                               sim.upload_grammar)


def test_gcache_hit_miss_compile_and_flat_ids():
    _, c = _gcache(n_slots=3)
    s1, up1 = c.acquire("s0", "r1")
    assert up1 and s1 == 1
    s2, up2 = c.acquire("s0", "r2")      # second pin: hit, same slot
    assert (s2, up2) == (s1, False)
    s3, up3 = c.acquire("s1", "r3")
    assert up3 and s3 == 2
    st = c.cache_stats()
    assert st["compiles"] == 2 and st["hits"] == 1
    assert c.census_ok()
    # flat ids index slot*max_states + state; slot 0 state 0 is the
    # reserved all-allow identity every free row carries
    assert c.flat_id(0, 0) == 0
    assert c.flat_id(s3, 5) == s3 * c.max_states + 5
    # the host automaton memo compiles once, probes never pin
    a = c.automaton("s2")
    assert c.automaton("s2") is a and not c.resident("s2")


def test_gcache_lru_eviction_order():
    """Released grammars park evictable in release order; a miss
    reclaims the LEAST recently parked first."""
    _, c = _gcache(n_slots=3)
    c.acquire("s0", "r0")
    c.acquire("s1", "r1")
    c.release("s0", "r0")
    c.release("s1", "r1")        # LRU order now: s0, s1
    slot_s0 = c.slot_of("s0")
    c.acquire("s2", "r2")        # evicts s0 (oldest parked)
    assert not c.resident("s0") and c.resident("s1")
    assert c.slot_of("s2") == slot_s0
    assert c.cache_stats()["evictions"] == 1
    # revival: re-acquiring the survivor is a hit, not a compile
    _, up = c.acquire("s1", "r3")
    assert not up
    assert c.census_ok()


def test_gcache_pin_survives_eviction_pressure():
    _, c = _gcache(n_slots=3)
    c.acquire("s0", "live")          # pinned throughout
    for i, name in enumerate(("s1", "s2", "s3", "s4")):
        c.acquire(name, f"r{i}")
        c.release(name, f"r{i}")
    assert c.resident("s0")
    assert c.cache_stats()["evictions"] == 3
    assert c.census_ok()


def test_gcache_budget_refusal_mutates_nothing():
    _, c = _gcache(n_slots=3)
    c.acquire("s0", "r0")
    c.acquire("s1", "r1")
    before = c.cache_stats()
    with pytest.raises(MemoryError, match="pinned"):
        c.acquire("s2", "r2")
    after = c.cache_stats()
    assert after["refusals"] == before["refusals"] + 1
    for k in ("resident_slots", "evictable_slots", "free_slots",
              "compiles"):
        assert after[k] == before[k]
    assert c.census_ok()
    c.release("s0", "r0")
    _, up = c.acquire("s2", "r2")    # now evicts s0
    assert up and c.census_ok()


def test_gcache_acquire_exception_safe():
    """A raising compile (a DFA bigger than the bank's max_states)
    must not leak the slot out of the census: free list / evictable
    LRU / stats restore exactly, the error stays loud, and the cache
    keeps serving."""
    store = GrammarStore({"small": {"type": "boolean"},
                          "small2": {"type": "null"},
                          "big": {"type": "string", "minLength": 1,
                                  "maxLength": 40}})
    sim = make_sim_serving(grammar_slots=3, grammar_states=12,
                           vocab=SIMVOCAB)
    c = GrammarCache(store, 3, 12,
                     TokenVocab.ascii_default(SIMVOCAB),
                     sim.init_grammar_bank, sim.upload_grammar)
    # free-list path
    before = c.cache_stats()
    with pytest.raises(ValueError, match="max_states"):
        c.acquire("big", "r0")
    assert c.cache_stats() == before and c.census_ok()
    # eviction path: fill both slots, park them, then fail an acquire
    c.acquire("small", "r1")
    c.acquire("small2", "r2")
    c.release("small", "r1")
    c.release("small2", "r2")
    before = c.cache_stats()
    with pytest.raises(ValueError, match="max_states"):
        c.acquire("big", "r3")
    assert c.cache_stats() == before and c.census_ok()
    # the would-be victim survived
    assert c.resident("small")
    _, up = c.acquire("small", "r4")
    assert not up


def test_gcache_rollback_and_took_compile():
    """A page-pool refusal AFTER acquire rolls the pin back; the
    compile the failed admission paid is attributed to the admission
    that eventually succeeds (one priced grammar_compile total)."""
    _, c = _gcache(n_slots=3)
    _, up = c.acquire("s0", "r0")
    assert up
    c.note_rollback("s0", "r0", up)
    assert c.census_ok()
    _, up2 = c.acquire("s0", "r0")       # the retry hits
    assert not up2
    assert c.took_compile("r0", up2)     # ...but owns the compile
    assert not c.took_compile("r0", False)  # consumed exactly once
    c.forget_pending("r0")               # idempotent on empty


def test_gcache_validation():
    store, c = _gcache()
    with pytest.raises(KeyError, match="unknown grammar"):
        c.acquire("nope", "r")
    c.acquire("s0", "r")
    with pytest.raises(ValueError, match="already pinned"):
        c.acquire("s0", "r")
    with pytest.raises(ValueError, match="no pin"):
        c.release("s0", "other")
    with pytest.raises(ValueError, match="n_slots"):
        GrammarCache(store, 1, 8, VO, lambda: None,
                     lambda b, s, g: b)
    with pytest.raises(ValueError, match="max_states"):
        GrammarCache(store, 3, 1, VO, lambda: None,
                     lambda b, s, g: b)
    with pytest.raises(ValueError, match="already registered"):
        store.add("s0", {"type": "boolean"})
    with pytest.raises(ValueError, match="non-empty"):
        GrammarStore({"": {"type": "boolean"}})
    with pytest.raises(ValueError, match="schema dict or EBNF"):
        GrammarStore({"bad": 42})


# --- sim engine: constrained decoding ---------------------------------------

def test_sim_constrained_streams_match_oracle_and_parse():
    """Engine streams are bit-equal to the closed-form sim oracle
    (masked emission + state advance + stop-at-accept) and every
    constrained stream detokenizes to schema-valid JSON."""
    store = _store(4)
    trace = _trace(seed=0, n=40)
    sim = make_sim_serving(max_len=96, page_size=8, slots=8,
                           vocab=SIMVOCAB, grammar_slots=5)
    eng = ServingEngine(serving=sim, slots=8, policy="paged",
                        clock="fixed", fixed_costs=dict(COSTS),
                        decode_chunk=4, grammar=store)
    res = eng.run(trace)
    assert len(res.outputs) == len(trace)
    assert res.grammar_stats["invariant_ok"]
    assert res.grammar_stats["compiles"] == 4
    schemas = _schemas(4)
    for r in trace:
        if r.schema is None:
            continue
        g = compile_schema(schemas[r.schema], VO)
        assert res.outputs[r.rid] == sim.expected_stream(
            r.prompt, r.max_new_tokens, grammar=g), r.rid
        assert schema_accepts(schemas[r.schema],
                              VO.decode(res.outputs[r.rid])), r.rid
        assert len(res.outputs[r.rid]) < r.max_new_tokens  # accepted
    rep = res.report()
    assert rep["constrained_streams"] == sum(
        1 for r in trace if r.schema is not None)
    assert rep["grammar_accepts"] == rep["constrained_streams"]
    assert 0.0 < rep["tokens_masked_frac"] <= 1.0


def test_sim_mixed_wave_free_row_parity():
    """Free rows riding the same batches as constrained rows are
    token-identical to a grammar=None engine — the mask never leaks
    across rows."""
    store = _store(4)
    trace = _trace(seed=2, n=50, free_frac=0.4)
    res = _sim_engine(grammar_slots=5, grammar=store).run(trace)
    free = [dataclasses.replace(r, schema=None) for r in trace
            if r.schema is None]
    plain = _sim_engine().run(free)
    assert free, "trace must carry free rows"
    for r in free:
        assert res.outputs[r.rid] == plain.outputs[r.rid], r.rid


def test_grammarless_engine_byte_identical():
    """The tentpole identity clause: grammar=None on a schema-less
    trace is byte-identical to PR 17 — and an engine WITH a grammar
    store still produces identical outputs/logs on that same trace
    (every row decodes through the all-allow identity)."""
    trace = synthesize_trace(seed=5, n_requests=12, vocab_size=SIMVOCAB,
                             prompt_len=(4, 12), output_len=(3, 8),
                             churn_frac=0.2)
    plain = _sim_engine().run(trace)
    assert plain.grammar_stats is None      # result shape unchanged
    rep = plain.report()
    assert not any(k.startswith("grammar") or k.startswith("constrained")
                   for k in rep)
    cons = _sim_engine(grammar_slots=3, grammar=_store()).run(trace)
    assert cons.outputs == plain.outputs
    assert cons.slot_log == plain.slot_log
    assert cons.decisions == plain.decisions
    assert cons.metrics.request_rows() == plain.metrics.request_rows()
    # no schema ever admitted -> the report block stays absent even
    # on the configured engine (the streams>0 convention)
    assert cons.report() == rep
    assert cons.grammar_stats["compiles"] == 0


def test_sim_determinism_and_bank_size_independence():
    """Same trace twice -> identical everything; a tight bank vs a
    roomy bank changes timing (compiles/evictions), never tokens."""
    store = _store(4)
    trace = _trace(seed=3, n=50)
    r1 = _sim_engine(grammar_slots=3, grammar=store).run(trace)
    r2 = _sim_engine(grammar_slots=3, grammar=store).run(trace)
    assert r1.outputs == r2.outputs
    assert r1.slot_log == r2.slot_log
    assert r1.grammar_stats == r2.grammar_stats
    assert r1.grammar_stats["evictions"] > 0  # the bank DID churn
    roomy = _sim_engine(grammar_slots=6, grammar=store).run(trace)
    assert roomy.outputs == r1.outputs
    assert roomy.grammar_stats["evictions"] == 0


def test_engine_save_log_no_grammar_fields(tmp_path):
    trace = synthesize_trace(seed=1, n_requests=6, vocab_size=SIMVOCAB)
    res = _sim_engine().run(trace)
    p = tmp_path / "log.jsonl"
    res.save_log(str(p))
    body = open(p).read()
    assert "grammar" not in body and "schema" not in body


def test_engine_validation():
    store = _store(2)
    trace = [Request(rid="q", arrival=0.0, prompt=(1, 2, 3),
                     max_new_tokens=4, schema="s0")]
    with pytest.raises(ValueError, match="without grammar="):
        _sim_engine(grammar_slots=3).run(trace)
    bad = [dataclasses.replace(trace[0], schema="zz")]
    with pytest.raises(ValueError, match="unknown schema"):
        _sim_engine(grammar_slots=3, grammar=store).run(bad)
    # grammar= without a grammar-enabled factory refuses at build
    with pytest.raises(ValueError, match="grammar-enabled"):
        _sim_engine(grammar=store)
    # dense policy refuses; routed coerces to paged
    with pytest.raises(ValueError, match="dense"):
        ServingEngine(serving=make_sim_serving(grammar_slots=3,
                                               vocab=SIMVOCAB),
                      slots=4, policy="dense", grammar=store,
                      clock="fixed")
    eng = ServingEngine(serving=make_sim_serving(grammar_slots=3,
                                                 vocab=SIMVOCAB),
                        slots=4, policy="routed", grammar=store,
                        clock="fixed")
    assert eng.policy.name == "paged"
    # a dispatched-ahead batch would mask with a stale DFA state
    with pytest.raises(ValueError, match="dispatch_ahead"):
        _sim_engine(grammar_slots=3, grammar=store,
                    dispatch_ahead=True)


def test_compile_paced_on_fixed_clock():
    """Each miss charges one grammar_compile unit; hits are free. Two
    same-schema requests arriving apart: the second's end-to-end span
    is exactly one unit shorter for identical work."""
    from paddle_tpu.inference import BatchingConfig
    store = GrammarStore({"only": {"enum": ["lo"]}})
    trace = [Request(rid="u0", arrival=0.0, prompt=(1, 2, 3, 4),
                     max_new_tokens=8, schema="only"),
             Request(rid="u1", arrival=50.0, prompt=(5, 6, 7, 8),
                     max_new_tokens=8, schema="only")]
    res = _sim_engine(grammar_slots=3, grammar=store,
                      admission=BatchingConfig(max_batch=1)).run(trace)
    rep = res.report()
    assert rep["constrained_streams"] == 2
    assert rep["grammar_compiles"] == 1
    assert rep["grammar_cache_hits"] == 1
    assert rep["grammar_cache_hit_rate"] == 0.5
    rows = {r["rid"]: r for r in res.metrics.request_rows()}
    assert rows["u0"]["e2e"] == pytest.approx(rows["u1"]["e2e"] + 1.0)
    # a single-value enum pins the whole stream: both decode '"lo"'
    for rid in ("u0", "u1"):
        assert VO.decode(res.outputs[rid]) == '"lo"'


def test_refusal_requeues_until_release():
    """More distinct in-flight schemas than usable slots: admission
    refuses, requeues, and completes everyone once pins release —
    nothing lost, census conserved, every stream still parses."""
    schemas = _schemas(4)
    store = GrammarStore(schemas)
    trace = [Request(rid=f"p{k}", arrival=0.0,
                     prompt=tuple(range(1, 5)), max_new_tokens=24,
                     schema=f"s{k}") for k in range(4)]
    res = _sim_engine(grammar_slots=3, grammar=store).run(trace)
    assert len(res.outputs) == 4
    assert res.grammar_stats["refusals"] > 0
    assert res.grammar_stats["invariant_ok"]
    for r in trace:
        assert schema_accepts(schemas[r.schema],
                              VO.decode(res.outputs[r.rid])), r.rid


def test_qos_degrade_never_breaks_json_and_publish_gauges():
    """The QoS loop threads grammar: the degrade floor keeps every
    clamped constrained budget at >= the automaton's shortest accept,
    so degraded streams still parse; publish() exports the
    constrained gauges only for constrained runs."""
    obs_metrics.REGISTRY.reset()
    schemas = _schemas(4)
    store = GrammarStore(schemas)
    trace = _trace(seed=4, n=60, overload=2.0)
    res = _sim_engine(grammar_slots=5, grammar=store,
                      scheduler=QoSScheduler(max_queue=16)).run(trace)
    assert res.grammar_stats["invariant_ok"]
    for r in trace:
        if r.schema is None or r.rid not in res.outputs:
            continue
        assert schema_accepts(schemas[r.schema],
                              VO.decode(res.outputs[r.rid])), r.rid
    rec = res.metrics.publish()
    assert rec["constrained_streams"] > 0
    g = obs_metrics.REGISTRY.gauge("serving_constrained_streams")
    assert g.value > 0
    # free-running publish never touches the constrained gauges
    pres = _sim_engine().run(
        synthesize_trace(seed=0, n_requests=4, vocab_size=SIMVOCAB))
    rec2 = pres.metrics.publish()
    assert not any(k.startswith("grammar") or k.startswith("constrained")
                   for k in rec2)


def test_grammar_floor_probe():
    """The scheduler floor seam: ``_grammar_floor`` is the compiled
    automaton's min_tokens for schema rows, None for free rows."""
    store = GrammarStore({"long": "root ::= [a-z]{8,30}"})
    eng = _sim_engine(grammar_slots=3, grammar=store)
    r = Request(rid="a", arrival=0.0, prompt=(1,), max_new_tokens=30,
                schema="long")
    assert eng._grammar_floor(r) == 8
    assert eng._grammar_floor(
        dataclasses.replace(r, schema=None)) is None


def test_adapter_schemas_defaults_compose_with_lora():
    """``adapter_schemas=`` gives an adapter a default output
    contract: its rows decode constrained with no per-request schema,
    an explicit Request.schema overrides, and the stream matches the
    lora+grammar oracle."""
    schemas = _schemas(2)
    store = GrammarStore(schemas)
    astore = AdapterStore({"bot": {"salt": 7919}})
    sim = make_sim_serving(max_len=96, page_size=8, slots=8,
                           vocab=SIMVOCAB, grammar_slots=3,
                           lora_slots=3)
    eng = ServingEngine(serving=sim, slots=8, policy="paged",
                        clock="fixed", fixed_costs=dict(COSTS),
                        decode_chunk=4, grammar=store,
                        adapters=astore,
                        adapter_schemas={"bot": "s0"})
    trace = [Request(rid="d0", arrival=0.0, prompt=(1, 2, 3, 4),
                     max_new_tokens=24, adapter="bot"),
             Request(rid="d1", arrival=0.0, prompt=(5, 6, 7, 8),
                     max_new_tokens=24, adapter="bot", schema="s1"),
             Request(rid="d2", arrival=0.0, prompt=(9, 10, 11),
                     max_new_tokens=6)]
    res = eng.run(trace)
    g0 = compile_schema(schemas["s0"], VO)
    g1 = compile_schema(schemas["s1"], VO)
    assert res.outputs["d0"] == sim.expected_stream(
        (1, 2, 3, 4), 24, adapter_salt=7919, grammar=g0)
    assert schema_accepts(schemas["s0"], VO.decode(res.outputs["d0"]))
    assert res.outputs["d1"] == sim.expected_stream(
        (5, 6, 7, 8), 24, adapter_salt=7919, grammar=g1)
    # the plain row stays free-running
    assert res.outputs["d2"] == sim.expected_stream((9, 10, 11), 6)
    assert res.report()["constrained_streams"] == 2
    # validation: every name must resolve at build
    with pytest.raises(ValueError, match="grammar="):
        ServingEngine(serving=sim, slots=8, policy="paged",
                      adapters=astore, adapter_schemas={"bot": "s0"})
    with pytest.raises(ValueError, match="without adapters="):
        ServingEngine(serving=sim, slots=8, policy="paged",
                      grammar=store, adapter_schemas={"bot": "s0"})
    with pytest.raises(ValueError, match="unknown adapter"):
        ServingEngine(serving=sim, slots=8, policy="paged",
                      grammar=store, adapters=astore,
                      adapter_schemas={"zz": "s0"})
    with pytest.raises(ValueError, match="unknown"):
        ServingEngine(serving=sim, slots=8, policy="paged",
                      grammar=store, adapters=astore,
                      adapter_schemas={"bot": "zz"})


# --- disaggregation + preemption --------------------------------------------

def test_disagg_handoff_moves_grammar_pin():
    """Grammar composes with disaggregated prefill->decode handoffs:
    the prefill worker masks the first token and unpins at export,
    the decode worker re-pins (compiling on first sight) and re-walks
    the DFA, streams stay bit-equal to a lone constrained engine, and
    both stages' slot censuses balance."""
    schemas = _schemas(2)
    store = GrammarStore(schemas)
    trace = [Request(rid=f"h{k}", arrival=float(k),
                     prompt=tuple(range(1 + k, 7 + k)),
                     max_new_tokens=24, schema=f"s{k % 2}")
             for k in range(8)]

    def spawn(name):
        return _sim_engine(grammar_slots=3, grammar=store,
                           prefill_chunk_budget=2)
    res = ClusterRouter(spawn, 2, placement="disaggregated",
                        roles={"r0": "prefill", "r1": "decode"},
                        kv_transfer_unit=0.05).run(trace)
    cen = res.census()
    assert cen["conserved"] and cen["pool_census_ok"]
    lone = _sim_engine(grammar_slots=3, grammar=store).run(trace)
    assert res.outputs() == lone.outputs
    for r in trace:
        assert schema_accepts(schemas[r.schema],
                              VO.decode(lone.outputs[r.rid]))
    for name in ("r0", "r1"):
        gst = res.results[name].grammar_stats
        assert gst["invariant_ok"]
        assert gst["compiles"] == 2       # each stage saw both once
        assert gst["resident_slots"] == 0  # every pin released
    # a decode stage WITHOUT the store cannot honor the contract

    def spawn_half(name):
        return _sim_engine(grammar_slots=3,
                           grammar=store if name == "r0" else None,
                           prefill_chunk_budget=2)
    with pytest.raises(RuntimeError, match="BOTH stages"):
        ClusterRouter(spawn_half, 2, placement="disaggregated",
                      roles={"r0": "prefill", "r1": "decode"},
                      kv_transfer_unit=0.05).run(trace)


def test_preempt_resume_reacquires_and_rewalks():
    """A constrained row preempted to the host arena resumes with its
    automaton re-acquired (a cache hit) and its DFA state re-derived
    from the resume prefix: the final stream is token-identical to a
    never-preempted run and still terminates at accept."""
    store = GrammarStore({"long": "root ::= [a-z]{24,30}"})
    costs = dict(COSTS, kv_pageout=0.5, kv_pagein=0.5)

    def build(hostmem):
        sim = make_sim_serving(max_len=96, page_size=8, slots=1,
                               vocab=SIMVOCAB, grammar_slots=3,
                               n_pool_pages=24, chunked_prefill=8)
        eng = ServingEngine(serving=sim, slots=1, policy="paged",
                            clock="fixed", fixed_costs=costs,
                            scheduler=QoSScheduler(), grammar=store,
                            hostmem=hostmem)
        return sim, eng
    trace = [Request(rid="lo", prompt=tuple(range(10, 26)),
                     max_new_tokens=30, arrival=0.0, tenant="t0",
                     priority=0, schema="long"),
             Request(rid="hi", prompt=tuple(range(40, 56)),
                     max_new_tokens=8, arrival=20.0, tenant="t1",
                     priority=9)]
    sim, eng = build(1 << 20)
    res = eng.run(trace)
    assert res.hostmem_stats["preempts"] >= 1
    assert "lo" in res.hostmem_stats["preempted_rids"]
    g = compile_grammar("root ::= [a-z]{24,30}", VO)
    assert res.outputs["lo"] == sim.expected_stream(
        tuple(range(10, 26)), 30, grammar=g)
    assert len(res.outputs["lo"]) == 24       # stopped at accept
    assert res.grammar_stats["hits"] >= 1     # resume re-pinned warm
    assert res.grammar_stats["invariant_ok"]
    # without the arena the same contention just queues "hi" — and
    # the constrained stream is identical either way
    res_n = build(None)[1].run(trace)
    assert res_n.outputs == res.outputs


# --- real tiny-llama factory -------------------------------------------------

@pytest.fixture(scope="module")
def grammar_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=VOCAB, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


def _real_factory(model, grammar=None, **kw):
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    return llama_serving_decode_factory(
        model, max_len=48, page_size=8, n_pool_pages=25,
        batch_capacity=4, chunked_prefill=8, grammar=grammar, **kw)


@pytest.fixture(scope="module")
def real_env(grammar_model):
    model, cfg = grammar_model
    gc = GrammarConfig(n_slots=3, max_states=64)
    return {"model": model, "cfg": cfg, "gc": gc,
            "store": GrammarStore(_schemas(3)),
            "srv": _real_factory(model, grammar=gc),
            "srv_plain": _real_factory(model)}


def _real_trace(seed=1, n=6, n_schemas=3):
    import numpy as np
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(4, 9))
        prompt = tuple(int(t) for t in rng.integers(1, VOCAB, plen))
        schema = None if i % 3 == 2 else f"s{i % n_schemas}"
        reqs.append(Request(rid=f"R{i:02d}", arrival=float(i),
                            prompt=prompt, max_new_tokens=20,
                            schema=schema))
    return reqs


def test_real_constrained_streams_parse(real_env):
    """The acceptance claim on the real factory: every constrained
    stream detokenizes to JSON its schema validates and stops at the
    automaton's accept; free rows in the same batches are bit-equal
    to the plain (no-grammar) factory."""
    vocab = TokenVocab.ascii_default(VOCAB)
    schemas = _schemas(3)
    trace = _real_trace()
    eng = ServingEngine(serving=real_env["srv"], slots=4,
                        policy="paged", clock="fixed",
                        grammar=real_env["store"])
    res = eng.run(trace)
    assert res.grammar_stats["invariant_ok"]
    n_con = 0
    for r in trace:
        if r.schema is None:
            continue
        n_con += 1
        text = vocab.decode(res.outputs[r.rid])
        assert schema_accepts(schemas[r.schema], text), (r.rid, text)
        assert len(res.outputs[r.rid]) < r.max_new_tokens
    assert n_con > 0
    plain = ServingEngine(serving=real_env["srv_plain"], slots=4,
                          policy="paged", clock="fixed")
    pres = plain.run([dataclasses.replace(r, schema=None)
                      for r in trace if r.schema is None])
    for r in trace:
        if r.schema is None:
            assert res.outputs[r.rid] == pres.outputs[r.rid], r.rid


def test_real_decode_program_cache_flat_across_schema_churn(real_env):
    """The recompile acceptance claim: the decode program cache stays
    flat as schemas churn (bank + flat state ids are jit inputs; the
    only extra entry is the n=1 clamp constrained turns decode at)."""
    trace = _real_trace(seed=2, n=9)
    eng = ServingEngine(serving=real_env["srv"], slots=4,
                        policy="paged", clock="fixed",
                        grammar=real_env["store"])
    eng.run(trace)
    assert eng._p_decode_n._cache_size() <= 2


def test_real_grammarless_identity(real_env):
    """schema=None rows through the all-allow identity are bit-equal
    to the PLAIN (no-grammar) factory — outputs, slot logs,
    decisions, records."""
    trace = [dataclasses.replace(r, schema=None)
             for r in _real_trace(seed=3, n=6)]
    plain = ServingEngine(serving=real_env["srv_plain"], slots=4,
                          policy="paged", clock="fixed").run(trace)
    cons = ServingEngine(serving=_real_factory(real_env["model"],
                                               grammar=real_env["gc"]),
                         slots=4, policy="paged", clock="fixed",
                         grammar=real_env["store"]).run(trace)
    assert cons.outputs == plain.outputs
    assert cons.slot_log == plain.slot_log
    assert cons.decisions == plain.decisions
    assert cons.metrics.request_rows() == plain.metrics.request_rows()
    assert plain.grammar_stats is None


def test_real_grammar_composes_with_tp(real_env):
    """A mesh-sharded factory with a replicated mask bank produces
    bit-equal constrained streams to the unsharded engine (the mask
    AND reshards into the row-parallel logits layout under GSPMD)."""
    from paddle_tpu.models.nlp.llama_decode import TPConfig
    trace = _real_trace(seed=5, n=4)
    srv_tp = _real_factory(real_env["model"], grammar=real_env["gc"],
                           tp=TPConfig((2,)))
    r1 = ServingEngine(serving=real_env["srv"], slots=4,
                       policy="paged", clock="fixed",
                       grammar=real_env["store"]).run(trace)
    r2 = ServingEngine(serving=srv_tp, slots=4, policy="paged",
                       clock="fixed",
                       grammar=real_env["store"]).run(trace)
    assert r2.outputs == r1.outputs
    assert r2.grammar_stats["invariant_ok"]


def test_grammar_config_and_hooks_validation(real_env):
    assert as_grammar_config(None) is None
    assert as_grammar_config((4, 32)) == GrammarConfig(n_slots=4,
                                                       max_states=32)
    assert as_grammar_config(GrammarConfig(3, 16)).n_slots == 3
    with pytest.raises(ValueError, match="n_slots"):
        GrammarConfig(n_slots=1)
    with pytest.raises(ValueError, match="max_states"):
        GrammarConfig(max_states=1)
    with pytest.raises(ValueError, match="grammar"):
        as_grammar_config("tight")
    # bank-hook shape validation at upload
    init, upload = grammar_bank_hooks(VOCAB, GrammarConfig(3, 12))
    bank = init()
    small = compile_schema({"type": "boolean"},
                           TokenVocab.ascii_default(VOCAB))
    bank = upload(bank, 1, small)
    big = compile_schema({"type": "string", "minLength": 1,
                          "maxLength": 40},
                         TokenVocab.ascii_default(VOCAB))
    with pytest.raises(ValueError, match="states"):
        upload(bank, 1, big)
    # engine-level grammar_config conflict with a prebuilt factory
    with pytest.raises(ValueError, match="conflicts"):
        ServingEngine(serving=real_env["srv"], slots=4,
                      policy="paged",
                      grammar_config=GrammarConfig(5, 64),
                      grammar=real_env["store"])


# --- trace report ------------------------------------------------------------

def test_trace_report_grammar_rows(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from trace_report import (grammar_schemas, grammar_summary,
                              load_trace as _load)
    store = _store(3)
    trace = _trace(seed=6, n=20, n_schemas=3)
    p = tmp_path / "tr.json"
    res = _sim_engine(grammar_slots=4, grammar=store,
                      trace=str(p)).run(trace)
    events = _load(str(p))
    row = grammar_summary(events)
    assert row is not None and row["bench"] == "trace_report_grammar"
    assert row["constrained_requests"] == sum(
        1 for r in trace if r.schema is not None)
    assert row["compiles"] == res.grammar_stats["compiles"]
    assert row["grammar_accepts"] == res.report()["grammar_accepts"]
    assert set(row["by_schema"]) <= {"s0", "s1", "s2"}
    sch = grammar_schemas(events)
    assert sch == {r.rid: r.schema for r in trace
                   if r.schema is not None}
    # absence: a free-running trace yields no row at all
    p2 = tmp_path / "tr2.json"
    _sim_engine(trace=str(p2)).run(
        synthesize_trace(seed=0, n_requests=4, vocab_size=SIMVOCAB))
    ev2 = _load(str(p2))
    assert grammar_summary(ev2) is None and grammar_schemas(ev2) == {}


# --- gate family -------------------------------------------------------------

def _gate_rows(ratio=1.0, parse=1.0, parity=True, census=True,
               compared=100, checked=500, programs=(1, 1),
               drop_arm=None):
    def arm(name):
        row = {"bench": "serving_grammar", "arm": name,
               "device": "sim", "conserved": True,
               "pool_census_ok": True}
        if name == "constrained":
            row["grammar_census_ok"] = census
        return row
    rows = [arm("constrained"), arm("free"),
            {"bench": "serving_grammar_summary",
             "constrained_vs_free_goodput": ratio,
             "constrained_parse_frac": parse,
             "constrained_checked": checked,
             "free_parity_ok": parity,
             "free_parity_compared": compared,
             "decode_programs_constrained": programs[0],
             "decode_programs_free": programs[1],
             "grammar_census_ok": census,
             "schemas": 4, "requests": 1000,
             "grammar_compiles": 4, "tokens_masked_frac": 0.99}]
    if drop_arm:
        rows = [r for r in rows if r.get("arm") != drop_arm]
    return rows


def test_gate_serving_grammar_pass_and_fails(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from bench_gate import check_serving_grammar

    assert check_serving_grammar(_gate_rows()) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["gate"] == "pass"
    assert out["constrained_vs_free_goodput"] == 1.0

    for rows, frag in (
            (_gate_rows(ratio=0.8), "floor"),
            (_gate_rows(parse=0.97), "parse"),
            (_gate_rows(checked=0), "parse"),
            (_gate_rows(parity=False), "DIVERGED"),
            (_gate_rows(compared=0), "DIVERGED"),
            (_gate_rows(programs=(3, 1)), "decode programs"),
            (_gate_rows(census=False), "census"),
            (_gate_rows(drop_arm="free"), "BOTH"),
            ([r for r in _gate_rows()
              if r["bench"] != "serving_grammar_summary"],
             "UNVERIFIED")):
        assert check_serving_grammar(rows) == 1
        out = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert out["gate"] == "FAIL"
        assert frag in out["reason"]


@pytest.mark.slow
def test_grammar_bench_arm_end_to_end(capsys):
    """The --grammar arm at reduced size: rows parse, the gate
    passes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_workload_bench as swb
    from bench_gate import check_serving_grammar
    rc = swb.main(["--cpu", "--grammar", "--grammar-requests", "400"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    arms = {r.get("arm") for r in rows
            if r.get("bench") == "serving_grammar"}
    assert arms == {"constrained", "free"}
    assert check_serving_grammar(rows) == 0
