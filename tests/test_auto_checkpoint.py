"""Auto-checkpoint: epoch-range resume, saver versioning/GC, HDFS mode.

~ reference test_auto_checkpoint*.py: train with train_epoch_range, kill
mid-run, restart under the same job id, assert completed epochs are
skipped and state (model + optimizer accumulators) is restored.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
    CheckpointSaver, ExeTrainStatus, train_epoch_range)


@pytest.fixture
def ckpt_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_AUTO_CHECKPOINT_DIR", str(tmp_path / "ac"))
    monkeypatch.setenv("PADDLE_JOB_ID", "job_test")
    monkeypatch.setenv("PADDLE_ENABLE_AUTO_CHECKPOINT", "1")
    return tmp_path


class TestSaver:
    def test_versioning_and_gc(self, ckpt_env):
        s = CheckpointSaver(max_ckpt_nums=2)
        for i in range(4):
            no = s.save_checkpoint(f"state{i}".encode(),
                                   ExeTrainStatus(epoch_no=i))
            assert no == i
        # only the newest 2 survive
        assert s._ckpt_nos() == [2, 3]
        blob, status = s.load_checkpoint()
        assert blob == b"state3" and status.epoch_no == 3
        blob2, st2 = s.load_checkpoint(ckpt_no=2)
        assert blob2 == b"state2" and st2.epoch_no == 2

    def test_empty_dir(self, ckpt_env):
        s = CheckpointSaver()
        blob, status = s.load_checkpoint()
        assert blob is None and status is None


class TestEpochRange:
    def _train(self, n_epochs, crash_after=None):
        paddle.seed(7)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=0.1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        ran = []
        for epoch in train_epoch_range(n_epochs, model=m, optimizer=opt):
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ran.append(epoch)
            if crash_after is not None and epoch == crash_after:
                break  # crash AT the yield: this epoch is NOT checkpointed
        return m, opt, ran

    def test_resume_skips_done_epochs(self, ckpt_env):
        m1, _, ran1 = self._train(6, crash_after=2)
        assert ran1 == [0, 1, 2]
        # "restart": fresh model, same job id. Epoch 2 broke before its
        # checkpoint landed, so it re-runs — exactly-once is only
        # guaranteed for epochs whose checkpoint completed.
        m2, opt2, ran2 = self._train(6)
        assert ran2 == [2, 3, 4, 5]  # epochs 0-1 durably done
        _, _, ran3 = self._train(6)
        assert ran3 == []  # everything already done

    def test_state_restored_on_resume(self, ckpt_env):
        # first run completes epoch 0 cleanly (checkpoint lands)
        m1, opt1, ran1 = self._train(1)
        assert ran1 == [0]
        w_saved = m1.weight.numpy().copy()
        paddle.seed(123)  # fresh model would differ without restore
        m2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.Adam(parameters=m2.parameters(),
                                     learning_rate=0.1)
        gen = train_epoch_range(3, model=m2, optimizer=opt2)
        first = next(gen)
        assert first == 1
        np.testing.assert_allclose(m2.weight.numpy(), w_saved, rtol=1e-6)
        assert opt2._step_count > 0  # optimizer state came back too
        gen.close()

    def test_disabled_env(self, ckpt_env, monkeypatch):
        monkeypatch.setenv("PADDLE_ENABLE_AUTO_CHECKPOINT", "0")
        _, _, ran = self._train(3)
        assert ran == [0, 1, 2]
        s = CheckpointSaver()
        assert s._ckpt_nos() == []  # nothing written when disabled


class TestPreemption:
    def test_guard_flag_and_boundary_save(self, ckpt_env):
        """In-process: a SIGTERM mid-epoch saves at the boundary and ends
        the loop; resume continues from the next epoch."""
        import os as _os
        import signal as _signal

        from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
            PreemptionGuard

        paddle.seed(7)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=0.1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        ran = []
        with PreemptionGuard() as guard:
            for epoch in train_epoch_range(10, model=m, optimizer=opt,
                                           guard=guard):
                loss = (m(x) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                ran.append(epoch)
                if epoch == 1:  # "preemption" arrives mid-epoch 1
                    _os.kill(_os.getpid(), _signal.SIGTERM)
        assert guard.preempted
        assert ran == [0, 1]  # loop ended at the boundary, not killed
        # epoch 1 WAS checkpointed (preemption forces the save)
        s = CheckpointSaver()
        _, status = s.load_checkpoint()
        assert status.epoch_no == 1
        # relaunch resumes at epoch 2
        m2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.Adam(parameters=m2.parameters(),
                                     learning_rate=0.1)
        gen = train_epoch_range(10, model=m2, optimizer=opt2)
        assert next(gen) == 2
        gen.close()

    def test_handlers_restored_on_exit(self, ckpt_env):
        import signal as _signal

        from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
            PreemptionGuard

        prev = _signal.getsignal(_signal.SIGTERM)
        with PreemptionGuard():
            assert _signal.getsignal(_signal.SIGTERM) != prev
        assert _signal.getsignal(_signal.SIGTERM) == prev


class TestHdfsMode:
    def test_upload_download_flow(self, ckpt_env, tmp_path, monkeypatch):
        # reuse the fake hadoop shim from test_fs
        from test_fs import FAKE_HADOOP
        import os
        import stat
        bindir = tmp_path / "bin"
        bindir.mkdir()
        sh = bindir / "hadoop"
        sh.write_text(FAKE_HADOOP)
        sh.chmod(sh.stat().st_mode | stat.S_IEXEC)
        (tmp_path / "hdfs").mkdir()
        monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
        monkeypatch.setenv("FAKE_HDFS_ROOT", str(tmp_path / "hdfs"))
        from paddle_tpu.distributed.fleet.utils.fs import HDFSClient
        s = CheckpointSaver(fs=HDFSClient(), root="/ckpts", job_id="j1",
                            max_ckpt_nums=2)
        s.save_checkpoint(b"abc", ExeTrainStatus(epoch_no=0),
                          local_cache_path=str(tmp_path / "cache"))
        blob, status = s.load_checkpoint(
            local_cache_path=str(tmp_path / "cache"))
        assert blob == b"abc" and status.epoch_no == 0
