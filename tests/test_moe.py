

def test_qwen2_moe_preset_shape():
    from paddle_tpu.models.nlp.moe import MoEConfig
    c = MoEConfig.qwen2_57b_a14b()
    # the published 57B-A14B routing shape: 64 routed top-8 + one
    # 20480-wide shared expert (8x the routed width)
    assert (c.num_experts, c.top_k, c.num_shared_experts) == (64, 8, 1)
    assert c.shared_expert_intermediate == 20480
    assert c.num_key_value_heads < c.num_attention_heads  # GQA


def test_wide_shared_expert_builds():
    import dataclasses
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.nlp.moe import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = dataclasses.replace(MoEConfig.deepseek_tiny(),
                              shared_expert_intermediate=96)
    m = MoEForCausalLM(cfg)
    # the shared SwiGLU takes the override width, not n_shared x inter
    gate = m.layers[0].shared_mlp.gate_proj.weight
    assert gate.shape[-1] == 96 or gate.shape[0] == 96, gate.shape
    tok = paddle.to_tensor(np.zeros((1, 8), np.int64))
    out = m(tok)
    assert out.shape[-1] == cfg.vocab_size


def test_topk_gating_reduces_to_top2():
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.incubate.distributed.models.moe import (top2_gating,
                                                            topk_gating)
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 1, (12, 6)), jnp.float32)
    d2, c2, a2 = top2_gating(logits, capacity=5)
    dk, ck, ak = topk_gating(logits, capacity=5, k=2)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(d2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ck), np.asarray(c2), atol=1e-6)
    np.testing.assert_allclose(float(ak), float(a2), rtol=1e-6)


def test_topk_gating_k4_routes_four_experts():
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.incubate.distributed.models.moe import topk_gating
    rng = np.random.default_rng(1)
    T, E, C, K = 16, 8, 16, 4
    logits = jnp.asarray(rng.normal(0, 1, (T, E)), jnp.float32)
    d, c, _ = topk_gating(logits, capacity=C, k=K)
    # ample capacity: every token hits EXACTLY k distinct experts
    per_token = np.asarray(d).sum(axis=(1, 2))
    np.testing.assert_array_equal(per_token, np.full(T, K))
    # combine weights are the normalized top-k gate probs (sum to 1)
    np.testing.assert_allclose(np.asarray(c).sum(axis=(1, 2)),
                               np.ones(T), rtol=1e-5)


def test_moe_forward_topk4():
    import dataclasses
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.nlp.moe import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = dataclasses.replace(MoEConfig.tiny(), num_experts=8, top_k=4)
    m = MoEForCausalLM(cfg)
    out = m(paddle.to_tensor(
        np.random.default_rng(0).integers(0, 256, (2, 8))))
    assert np.isfinite(np.asarray(out._value)).all()
