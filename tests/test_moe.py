

def test_qwen2_moe_preset_shape():
    from paddle_tpu.models.nlp.moe import MoEConfig
    c = MoEConfig.qwen2_57b_a14b()
    # the published 57B-A14B routing shape: 64 routed top-8 + one
    # 20480-wide shared expert (8x the routed width)
    assert (c.num_experts, c.top_k, c.num_shared_experts) == (64, 8, 1)
    assert c.shared_expert_intermediate == 20480
    assert c.num_key_value_heads < c.num_attention_heads  # GQA


def test_wide_shared_expert_builds():
    import dataclasses
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.nlp.moe import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = dataclasses.replace(MoEConfig.deepseek_tiny(),
                              shared_expert_intermediate=96)
    m = MoEForCausalLM(cfg)
    # the shared SwiGLU takes the override width, not n_shared x inter
    gate = m.layers[0].shared_mlp.gate_proj.weight
    assert gate.shape[-1] == 96 or gate.shape[0] == 96, gate.shape
    tok = paddle.to_tensor(np.zeros((1, 8), np.int64))
    out = m(tok)
    assert out.shape[-1] == cfg.vocab_size
