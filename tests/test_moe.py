

def test_qwen2_moe_preset_shape():
    from paddle_tpu.models.nlp.moe import MoEConfig
    c = MoEConfig.qwen2_57b_a14b()
    # the published 57B-A14B routing shape: 64 routed top-8 + one
    # 20480-wide shared expert (8x the routed width)
    assert (c.num_experts, c.top_k, c.num_shared_experts) == (64, 8, 1)
    assert c.shared_expert_intermediate == 20480
    assert c.num_key_value_heads < c.num_attention_heads  # GQA


def test_wide_shared_expert_builds():
    import dataclasses
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.nlp.moe import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = dataclasses.replace(MoEConfig.deepseek_tiny(),
                              shared_expert_intermediate=96)
    m = MoEForCausalLM(cfg)
    # the shared SwiGLU takes the override width, not n_shared x inter
    gate = m.layers[0].shared_mlp.gate_proj.weight
    assert gate.shape[-1] == 96 or gate.shape[0] == 96, gate.shape
    tok = paddle.to_tensor(np.zeros((1, 8), np.int64))
    out = m(tok)
    assert out.shape[-1] == cfg.vocab_size


def test_topk_gating_reduces_to_top2():
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.incubate.distributed.models.moe import (top2_gating,
                                                            topk_gating)
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 1, (12, 6)), jnp.float32)
    d2, c2, a2 = top2_gating(logits, capacity=5)
    dk, ck, ak = topk_gating(logits, capacity=5, k=2)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(d2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ck), np.asarray(c2), atol=1e-6)
    np.testing.assert_allclose(float(ak), float(a2), rtol=1e-6)


def test_topk_gating_k4_routes_four_experts():
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.incubate.distributed.models.moe import topk_gating
    rng = np.random.default_rng(1)
    T, E, C, K = 16, 8, 16, 4
    logits = jnp.asarray(rng.normal(0, 1, (T, E)), jnp.float32)
    d, c, _ = topk_gating(logits, capacity=C, k=K)
    # ample capacity: every token hits EXACTLY k distinct experts
    per_token = np.asarray(d).sum(axis=(1, 2))
    np.testing.assert_array_equal(per_token, np.full(T, K))
    # combine weights are the normalized top-k gate probs (sum to 1)
    np.testing.assert_allclose(np.asarray(c).sum(axis=(1, 2)),
                               np.ones(T), rtol=1e-5)


def test_moe_forward_topk4():
    import dataclasses
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.nlp.moe import MoEConfig, MoEForCausalLM
    paddle.seed(0)
    cfg = dataclasses.replace(MoEConfig.tiny(), num_experts=8, top_k=4)
    m = MoEForCausalLM(cfg)
    out = m(paddle.to_tensor(
        np.random.default_rng(0).integers(0, 256, (2, 8))))
    assert np.isfinite(np.asarray(out._value)).all()


class TestMoETrainStepFactory:
    """Compiled MoE pretraining step (BASELINE config 5): causal-LM CE +
    gate aux loss, adamw, params per sharding annotation — expert
    parallelism comes from MoELayer's P('expert', ...) specs with no
    factory special-casing."""

    def test_loss_decreases_on_expert_parallel_mesh(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import (MoEConfig, MoEForCausalLM,
                                           moe_train_step_factory)
        import numpy as np
        devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("data", "expert"))
        paddle.seed(0)
        cfg = MoEConfig.deepseek_tiny()
        m = MoEForCausalLM(cfg)
        params, opt, step = moe_train_step_factory(m, mesh,
                                                   learning_rate=3e-3)
        rng = np.random.default_rng(0)
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 33)),
                          jnp.int32)
        losses = []
        for _ in range(5):
            # real next-token objective: callers shift (factory scores
            # position-aligned labels, the llama/bert family convention)
            params, opt, loss = step(params, opt, tok[:, :-1],
                                     tok[:, 1:])
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_init_health_first_loss_near_ln_vocab(self):
        """Round-4 verdict weak #1: the tied output head over an N(0,1)
        embedding gave initial logits with std ~ sqrt(H) and a step-0
        loss ~9x ln V. With the sigma=0.02 tied-table init the first
        step must sit within 2x of the uniform-prediction loss ln V."""
        import math

        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import (MoEConfig, MoEForCausalLM,
                                           moe_train_step_factory)
        devs = np.asarray(jax.devices()[:2]).reshape(2)
        mesh = Mesh(devs, ("expert",))
        paddle.seed(0)
        cfg = MoEConfig.deepseek_tiny()
        m = MoEForCausalLM(cfg)
        params, opt, step = moe_train_step_factory(m, mesh)
        rng = np.random.default_rng(0)
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)),
                          jnp.int32)
        _, _, loss = step(params, opt, tok[:, :-1], tok[:, 1:])
        assert float(loss) < 2.0 * math.log(cfg.vocab_size), float(loss)

    def test_activated_params_counts_topk_fraction(self):
        import numpy as _np

        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import MoEConfig, MoEForCausalLM
        paddle.seed(0)
        cfg = MoEConfig.deepseek_tiny()  # 8 experts top-2
        m = MoEForCausalLM(cfg)
        total = sum(int(_np.prod(p.shape))
                    for p in m.state_dict().values())
        act = m.activated_params()
        routed = sum(int(_np.prod(p.shape))
                     for n, p in m.state_dict().items()
                     if ".mlp.w_in" in n or ".mlp.w_out" in n)
        assert routed > 0
        assert act == total - routed + routed * 2 // 8
