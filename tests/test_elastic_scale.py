"""Elastic scale e2e: membership change -> pod relaunch with rewritten
rank envs -> resume from auto-checkpoint.

~ reference elastic/manager.py:34 (--np min:max) + :130 (rank-env rewrite
on scale events). A pod launched with ``--np 1:2`` trains while a second
node joins the TCPStore membership registry (scale UP: trainers relaunch
with PADDLE_WORLD_SIZE=2) and later dies (heartbeat stops -> scale DOWN:
back to world 1). Training progress rides the auto-checkpoint across every
relaunch. Collective execution across the processes is covered separately
by test_multihost_mesh.py; this test validates the launcher's elastic
contract: watch -> terminate -> env rewrite -> relaunch -> resume.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys
import textwrap
import time

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    import time
    sys.path.insert(0, "/root/repo")
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    out_dir = os.environ["TEST_OUT_DIR"]
    paddle.seed(5)
    m = nn.Linear(8, 2)
    opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                learning_rate=0.05)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))

    log_path = os.path.join(out_dir, "epochs.jsonl")
    for epoch in train_epoch_range(28, model=m, optimizer=opt):
        loss = (m(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        with open(log_path, "a") as f:
            f.write(json.dumps({
                "epoch": epoch, "pid": os.getpid(),
                "world": int(os.environ["PADDLE_WORLD_SIZE"]),
                "rank": int(os.environ["PADDLE_GLOBAL_RANK"]),
            }) + "\\n")
        time.sleep(0.7)
""")

# a second "node": registers in the membership store, heartbeats for a
# while, then exits abruptly (no deregistration — death is detected by
# heartbeat expiry, like a real node failure)
PEER = textwrap.dedent("""
    import os
    import sys
    import time
    sys.path.insert(0, "/root/repo")
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.distributed.fleet.elastic import ElasticManager

    store = TCPStore("127.0.0.1", int(sys.argv[1]), is_master=False)
    mgr = ElasticManager(store, "zz-nodeB", (1, 2),
                         heartbeat_interval=0.5, dead_after=3.0)
    mgr.start()
    time.sleep(float(sys.argv[2]))
    os._exit(0)
""")


@pytest.mark.dist_retry(n=2)
def test_scale_up_down_relaunch_resume(tmp_path):
    # n=2: the 0.5s-heartbeat/3s-dead-after membership loop is the most
    # load-sensitive e2e in the suite — observed failing (twice in a
    # row) only when a full parallel pytest run shared this 1-core host
    script = tmp_path / "trainer.py"
    script.write_text(TRAINER)
    peer = tmp_path / "peer.py"
    peer.write_text(PEER)
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_AUTO_CHECKPOINT_DIR"] = str(tmp_path / "ckpt")
    env["PADDLE_JOB_ID"] = "elastic_scale_job"
    master_port = 34815
    pod = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--master", f"127.0.0.1:{master_port}",
         "--nproc_per_node", "1", "--elastic_level", "1",
         "--np", "1:2", "--elastic_node_id", "aa-nodeA", str(script)],
        cwd="/root/repo", env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        log = tmp_path / "epochs.jsonl"
        deadline = time.time() + 60
        while time.time() < deadline:
            if log.exists() and len(log.read_text().splitlines()) >= 2:
                break
            time.sleep(0.3)
        assert log.exists(), "trainer never produced epochs"

        # scale UP: nodeB joins the membership store; kill it only once
        # the relaunched world-2 trainer has actually logged an epoch
        # (event-driven, not sleep-tuned — this host has one CPU core and
        # relaunch latency varies with load)
        peer_proc = subprocess.Popen(
            [sys.executable, str(peer), str(master_port + 7), "120.0"],
            cwd="/root/repo", env=env)
        try:
            deadline = time.time() + 90
            while time.time() < deadline:
                lines = [json.loads(ln) for ln in
                         log.read_text().splitlines()]
                if any(ln["world"] == 2 for ln in lines):
                    break
                time.sleep(0.4)
            else:
                raise AssertionError("never observed a world=2 epoch")
        finally:
            peer_proc.kill()  # abrupt death -> heartbeat expiry

        # scale DOWN is as load-sensitive as scale UP: wait (event-driven)
        # for the post-death world=1 relaunch to log an epoch before the
        # trainer's epoch budget can run out at world=2 — the failure
        # mode observed under a full parallel suite on this 1-core host
        deadline = time.time() + 90
        while time.time() < deadline:
            lines = [json.loads(ln) for ln in log.read_text().splitlines()]
            after_up = lines[max(i for i, ln in enumerate(lines)
                                 if ln["world"] == 2):]
            if any(ln["world"] == 1 for ln in after_up):
                break
            if pod.poll() is not None:
                break  # pod already finished; asserts below judge the log
            time.sleep(0.4)

        out, err = pod.communicate(timeout=180)
        assert pod.returncode == 0, out + "\n" + err
    finally:
        if pod.poll() is None:
            pod.kill()

    lines = [json.loads(ln) for ln in
             (tmp_path / "epochs.jsonl").read_text().splitlines()]
    worlds = [ln["world"] for ln in lines]
    epochs = [ln["epoch"] for ln in lines]
    pids = {ln["pid"] for ln in lines}
    assert "elastic scale" in err, err
    # membership changes rewrote the world size: 1 -> 2 (join) -> 1 (death)
    assert 2 in worlds, f"never scaled up: {worlds}"
    assert worlds[0] == 1 and worlds[-1] == 1, worlds
    assert len(pids) >= 3, "expected a relaunch per scale event"
    # auto-checkpoint resume: epochs never regress by more than the one
    # in-flight epoch, and the run completes all 28
    for a, b in zip(epochs, epochs[1:]):
        assert b >= a - 1, f"lost progress: {epochs}"
    assert epochs[-1] == 27, epochs
    # rank stays the sorted-membership index of nodeA ("aa-" < "zz-")
    assert all(ln["rank"] == 0 for ln in lines)
