"""Compiled ResNet train step: functional BN-stat threading + SGD momentum.

~ reference ResNet training recipe (python/paddle/vision/models/resnet.py
+ optimizer/momentum.py); BN running stats are mutable op outputs there
(phi batch_norm kernel) — here they are threaded functionally through the
jitted step.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.vision.models import resnet18
from paddle_tpu.vision.models.resnet import resnet_train_step_factory


def _data(B=8, hw=32, classes=10, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    # class-template images + noise so the loss can actually fall
    templates = rng.normal(0, 1, (classes, 3, hw, hw)).astype(np.float32)
    y = rng.integers(0, classes, B)
    x = (templates[y] + 0.3 * rng.normal(0, 1, (B, 3, hw, hw))).astype(dtype)
    return jnp.asarray(x), jnp.asarray(y, jnp.int32)


def test_loss_decreases_and_bn_stats_update():
    paddle.seed(0)
    model = resnet18(num_classes=10)
    mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), ("data",))
    params, buffers, opt, step = resnet_train_step_factory(
        model, mesh, learning_rate=0.05)
    x, y = _data()
    mean0 = np.asarray(
        buffers["bn1._mean"] if "bn1._mean" in buffers
        else next(v for k, v in buffers.items() if k.endswith("_mean")))
    losses = []
    for _ in range(6):
        params, buffers, opt, loss = step(params, buffers, opt, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(opt["step"]) == 6
    mean_k = next(k for k in buffers if k.endswith("_mean"))
    assert not np.allclose(np.asarray(buffers[mean_k]), mean0), \
        "BN running stats must update through the compiled step"
    # velocity is live (momentum accumulated)
    vel = next(iter(opt["velocity"].values()))
    assert float(jnp.max(jnp.abs(vel))) > 0


def test_bn_stat_update_matches_eager_oracle():
    """One compiled step's running-stat update == the eager formula
    momentum*old + (1-momentum)*batch_stat (f32 batch stats)."""
    paddle.seed(1)
    model = resnet18(num_classes=10)
    mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), ("data",))
    params, buffers, opt, step = resnet_train_step_factory(model, mesh)
    x, y = _data(seed=1)

    # eager oracle: run the model in train mode once and read the stats
    oracle = resnet18(num_classes=10)
    paddle.seed(1)
    for (k, pv) in oracle.state_dict().items():
        src = params.get(k, buffers.get(k))
        pv.set_value(paddle.to_tensor(np.asarray(src)))
    oracle.train()
    oracle(paddle.to_tensor(np.asarray(x)))
    _, buffers2, _, _ = step(params, buffers, opt, x, y)
    for k, v in oracle.state_dict().items():
        if k.endswith("_mean") or k.endswith("_variance"):
            np.testing.assert_allclose(np.asarray(buffers2[k]),
                                       v.numpy(), rtol=2e-5, atol=2e-5)


def test_bf16_cast_keeps_bn_buffers_f32_and_runs():
    paddle.seed(2)
    model = resnet18(num_classes=10)
    model.to(dtype="bfloat16")
    mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), ("data",))
    params, buffers, opt, step = resnet_train_step_factory(model, mesh)
    assert all(v.dtype == jnp.bfloat16 for v in params.values())
    assert all(v.dtype == jnp.float32 for v in buffers.values())
    x, y = _data(dtype=np.float32)
    x = x.astype(jnp.bfloat16)
    # bf16 params carry f32 masters: velocity alone can't represent
    # updates below bf16 resolution
    assert set(opt["master"]) == set(params)
    m0 = np.asarray(next(iter(opt["master"].values())))
    params, buffers, opt, loss = step(params, buffers, opt, x, y)
    assert np.isfinite(float(loss))
    # stats stayed f32 through the step
    assert all(v.dtype == jnp.float32 for v in buffers.values())
    assert all(v.dtype == jnp.float32 for v in opt["master"].values())
    assert not np.allclose(np.asarray(next(iter(opt["master"].values()))),
                           m0)


def test_eager_bf16_bn_buffers_keep_dtype():
    """Eager train-mode forward must not promote a bf16 model's running
    stats to f32 (the blend casts back to the buffer dtype)."""
    from paddle_tpu import nn
    bn = nn.BatchNorm2D(4)
    bn.to(dtype="bfloat16")
    bn.train()
    x = paddle.cast(paddle.to_tensor(
        np.random.default_rng(3).normal(0, 1, (2, 4, 8, 8))), "bfloat16")
    bn(x)
    assert str(bn._mean.dtype).endswith("bfloat16"), bn._mean.dtype


def test_bf16_activations_stay_bf16_through_bn():
    """The f32-internal BN must hand back storage-dtype activations —
    otherwise one BN silently upcasts the rest of the network."""
    from paddle_tpu.nn import functional as F
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(0, 1, (2, 4, 8, 8)))
    x = paddle.cast(x, "bfloat16")
    rm = paddle.to_tensor(np.zeros(4, np.float32))
    rv = paddle.to_tensor(np.ones(4, np.float32))
    out = F.batch_norm(x, rm, rv, training=True)
    assert str(out.dtype).endswith("bfloat16"), out.dtype
