"""Multi-process tensor-parallel training parity.

~ reference hybrid TP tests (test_parallel_dygraph_mp_layers.py over
spawned ranks): a 2-process mesh shards a 2-layer MLP column/row-wise
over the 'model' axis (GSPMD inserts the mp allreduce the reference's
RowParallelLinear does by hand); per-step losses must match the dense
single-process oracle.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys
import textwrap

import numpy as np

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    sys.path.insert(0, "/root/repo")
    import jax
    jax.config.update("jax_platforms", "cpu")

    rank = int(os.environ.get("PADDLE_GLOBAL_RANK", "0"))
    world = int(os.environ.get("PADDLE_WORLD_SIZE", "1"))
    if world > 1:
        host, port = os.environ["PADDLE_MASTER"].split(":")
        os.environ["PADDLE_MASTER"] = f"{host}:{int(port) + 37}"

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    n_dev = world if world > 1 else 1
    devs = np.asarray(jax.devices()[:n_dev])
    mesh = Mesh(devs, ("model",))

    # identical init everywhere
    rng = np.random.default_rng(11)
    W1 = jnp.asarray(rng.standard_normal((16, 32)) * 0.1, jnp.float32)
    W2 = jnp.asarray(rng.standard_normal((32, 4)) * 0.1, jnp.float32)
    # Megatron layout: W1 column-sharded, W2 row-sharded over 'model'
    W1 = jax.device_put(W1, NamedSharding(mesh, P(None, "model")))
    W2 = jax.device_put(W2, NamedSharding(mesh, P("model", None)))
    repl = NamedSharding(mesh, P())

    def loss_fn(params, x, y):
        W1, W2 = params
        h = jax.nn.relu(x @ W1)
        pred = h @ W2      # GSPMD inserts the row-parallel allreduce
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(params, x, y):
        l, g = jax.value_and_grad(loss_fn)(params, x, y)
        return l, [p - 0.1 * gp for p, gp in zip(params, g)]

    params = [W1, W2]
    data = np.random.default_rng(5)
    x = jax.device_put(jnp.asarray(
        data.standard_normal((8, 16)), jnp.float32), repl)
    y = jax.device_put(jnp.asarray(
        data.standard_normal((8, 4)), jnp.float32), repl)
    losses = []
    for _ in range(4):
        l, params = step(params, x, y)
        losses.append(float(np.asarray(jax.device_get(l))))

    out = os.environ["TEST_OUT_DIR"]
    with open(os.path.join(out, f"loss_rank{rank}.json"), "w") as f:
        json.dump(losses, f)
""")


def _run(tmp_path, nproc):
    script = tmp_path / "tp_trainer.py"
    script.write_text(TRAINER)
    out = tmp_path / f"np{nproc}"
    out.mkdir()
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(out)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_GLOBAL_RANK", None)
    env.pop("PADDLE_WORLD_SIZE", None)
    env.pop("XLA_FLAGS", None)  # exactly one local CPU device per process
    if nproc == 1:
        proc = subprocess.run([sys.executable, str(script)],
                              cwd="/root/repo", env=env,
                              capture_output=True, text=True, timeout=300)
    else:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", str(nproc), str(script)],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=300)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    losses = []
    for r in range(nproc):
        p = out / f"loss_rank{r}.json"
        assert p.exists(), \
            f"rank {r} wrote no losses: {proc.stdout}\n{proc.stderr}"
        losses.append(json.loads(p.read_text()))
    return np.asarray(losses)


@pytest.mark.dist_retry(n=1)
def test_tp_two_proc_loss_parity(tmp_path):
    single = _run(tmp_path, 1)[0]
    two = _run(tmp_path, 2)
    # every rank sees the replicated global loss; must equal the dense
    # single-process trajectory step for step
    np.testing.assert_allclose(two[0], two[1], rtol=1e-6)
    np.testing.assert_allclose(two[0], single, rtol=1e-4, atol=1e-6)
    assert single[-1] < single[0]
