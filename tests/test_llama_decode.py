"""Compiled KV-cache generation (fused_multi_transformer analog).

Greedy decode must match the eager O(S^2) LlamaForCausalLM.generate
token for token; sampling paths must be deterministic per key.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.nlp.llama_decode import llama_decode_factory


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


class TestCompiledDecode:
    def test_greedy_matches_eager_generate(self, model):
        gen = llama_decode_factory(model, max_len=64)
        prompt = np.random.default_rng(0).integers(
            0, 97, (2, 7)).astype(np.int32)
        fast = np.asarray(gen(jnp.asarray(prompt), max_new_tokens=8))
        slow = model.generate(paddle.to_tensor(prompt),
                              max_new_tokens=8).numpy()
        np.testing.assert_array_equal(fast, slow)

    def test_prompt_preserved(self, model):
        gen = llama_decode_factory(model, max_len=32)
        prompt = np.arange(5, dtype=np.int32)[None]
        out = np.asarray(gen(jnp.asarray(prompt), max_new_tokens=4))
        np.testing.assert_array_equal(out[:, :5], prompt)
        assert out.shape == (1, 9)

    def test_sampling_deterministic_per_key(self, model):
        gen = llama_decode_factory(model, max_len=32)
        prompt = jnp.asarray(np.ones((1, 4), np.int32))
        a = np.asarray(gen(prompt, 6, key=jax.random.PRNGKey(7),
                           temperature=1.0, top_k=5))
        b = np.asarray(gen(prompt, 6, key=jax.random.PRNGKey(7),
                           temperature=1.0, top_k=5))
        c = np.asarray(gen(prompt, 6, key=jax.random.PRNGKey(8),
                           temperature=1.0, top_k=5))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # different key, different draw

    def test_overflow_guard(self, model):
        gen = llama_decode_factory(model, max_len=8)
        with pytest.raises(ValueError, match="max_len"):
            gen(jnp.asarray(np.ones((1, 6), np.int32)), max_new_tokens=5)


class TestRollingWindowCache:
    """sliding_window decode uses a rolling KV buffer (O(window) memory,
    unbounded length); generations must match the eager windowed model
    recomputing full attention every step."""

    def _greedy_oracle(self, model, tokens, n_new):
        import paddle_tpu as paddle
        cur = np.asarray(tokens)
        for _ in range(n_new):
            logits = model(paddle.to_tensor(cur)).numpy()
            nxt = logits[:, -1].argmax(-1).astype(cur.dtype)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
        return cur

    @pytest.mark.parametrize("s0,new,window", [
        (6, 10, 8),    # generation crosses the wrap boundary
        (12, 6, 8),    # prompt longer than the window (rolled prefill)
    ])
    def test_matches_eager_windowed_oracle(self, s0, new, window):
        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
        cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                               kv_heads=2)
        cfg.sliding_window = window
        paddle.seed(3)
        model = LlamaForCausalLM(cfg)
        model.eval()
        gen = llama_decode_factory(model, max_len=64)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 97, (2, s0)).astype(np.int32)
        got = np.asarray(gen(prompt, max_new_tokens=new))
        want = self._greedy_oracle(model, prompt, new)
        np.testing.assert_array_equal(got, want)

    def test_unbounded_generation_past_max_len(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
        cfg = LlamaConfig.tiny(vocab=61, hidden=32, layers=1, heads=2,
                               kv_heads=2)
        cfg.sliding_window = 8
        paddle.seed(4)
        model = LlamaForCausalLM(cfg)
        model.eval()
        gen = llama_decode_factory(model, max_len=16)
        prompt = np.ones((1, 4), np.int32)
        out = np.asarray(gen(prompt, max_new_tokens=40))  # 44 > max_len
        assert out.shape == (1, 44)


def test_top_p_nucleus_sampling():
    """top_p truncation: a tiny nucleus reduces to argmax; a moderate one
    only ever samples tokens inside the nucleus."""
    import jax
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=61, hidden=32, layers=1, heads=2,
                           kv_heads=2)
    paddle.seed(6)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen = llama_decode_factory(model, max_len=32)
    prompt = np.ones((2, 4), np.int32)
    greedy = np.asarray(gen(prompt, max_new_tokens=8))
    tiny_p = np.asarray(gen(prompt, max_new_tokens=8,
                            key=jax.random.PRNGKey(1), temperature=1.0,
                            top_p=1e-6))
    np.testing.assert_array_equal(tiny_p, greedy)
    # moderate nucleus still generates valid tokens and differs from
    # greedy for at least one position across keys
    outs = [np.asarray(gen(prompt, max_new_tokens=8,
                           key=jax.random.PRNGKey(k), temperature=1.0,
                           top_p=0.9)) for k in range(3)]
    assert any(not np.array_equal(o, greedy) for o in outs)
    for o in outs:
        assert o.min() >= 0 and o.max() < cfg.vocab_size


def test_top_p_zero_clamps_to_greedy():
    import jax
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=61, hidden=32, layers=1, heads=2,
                           kv_heads=2)
    paddle.seed(6)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen = llama_decode_factory(model, max_len=32)
    prompt = np.ones((2, 4), np.int32)
    greedy = np.asarray(gen(prompt, max_new_tokens=6))
    zero_p = np.asarray(gen(prompt, max_new_tokens=6,
                            key=jax.random.PRNGKey(2), temperature=1.0,
                            top_p=0.0))
    np.testing.assert_array_equal(zero_p, greedy)


def test_eos_early_stop_batched():
    """Rows that emit EOS pad from then on; the loop exits early when all
    rows are done (fewer decode steps than max_new_tokens)."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=61, hidden=32, layers=1, heads=2,
                           kv_heads=2)
    paddle.seed(6)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen = llama_decode_factory(model, max_len=64)
    prompt = np.ones((2, 4), np.int32)
    # find the model's first greedy token and use it as "EOS" so the
    # very first decode step finishes every row
    first = np.asarray(gen(prompt, max_new_tokens=1))[:, -1]
    # identical prompt rows + greedy decode: first tokens must match
    assert first[0] == first[1]
    out = np.asarray(gen(prompt, max_new_tokens=40,
                         eos_token_id=int(first[0])))
    assert out.shape[1] < 4 + 40  # stopped early (8-step poll bound)
    assert int(out[0, 4]) == int(first[0])  # EOS itself is emitted
    assert (out[:, 5:] == 0).all()  # pads after EOS
    # pad semantics: with an eos that never fires, shape is full length
    out2 = np.asarray(gen(prompt, max_new_tokens=5, eos_token_id=60))
    assert out2.shape == (2, 9)


def test_int8_kv_cache_close_to_fp():
    """kv_cache_dtype='int8': the quantized cache's LOGITS track the fp
    cache within ~1% under teacher forcing, and the stored cache really
    is int8 (half the bytes).

    Teacher-forced logit error is the honest measure here: a random
    2-layer model's greedy trajectory is chaotic (near-uniform logits),
    so token-exact match over 8 free-running steps flips on numerics
    noise image-to-image — while the cache's actual quantization error
    is deterministic and small."""
    import jax.numpy as jnp

    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=97, hidden=64, layers=2, heads=4,
                           kv_heads=2)
    paddle.seed(12)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen_fp = llama_decode_factory(model, max_len=32)
    gen_q = llama_decode_factory(model, max_len=32, kv_cache_dtype="int8")
    prompt = np.asarray(
        np.random.default_rng(1).integers(0, 97, (2, 6)), np.int32)
    seq = np.asarray(gen_fp(prompt, max_new_tokens=8))

    def drive(parts):
        """Prefill + 7 decode steps teacher-forced on the fp tokens."""
        kc = parts["init_caches"](2, jnp.float32)
        vc = parts["init_caches"](2, jnp.float32)
        lg, kc, vc = parts["prefill"](parts["outer"], parts["layers"],
                                      jnp.asarray(prompt), kc, vc)
        logits = [np.asarray(lg)]
        for i in range(7):
            lg, kc, vc = parts["decode_step"](
                parts["outer"], parts["layers"],
                jnp.asarray(seq[:, 6 + i]), jnp.asarray(6 + i), kc, vc)
            logits.append(np.asarray(lg))
        return np.stack(logits, 1), kc

    lf, _ = drive(gen_fp._parts)
    lq, kc_q = drive(gen_q._parts)
    # prefill logits are exact (the current block overlays unquantized);
    # decode steps read the int8 past — error stays ~1% of logit scale
    np.testing.assert_array_equal(lf[:, 0], lq[:, 0])
    assert np.argmax(lf[:, 0], -1).tolist() == \
        np.argmax(lq[:, 0], -1).tolist()
    rel = np.abs(lf - lq).max() / np.abs(lf).max()
    assert rel < 0.05, f"int8 KV logit error {rel:.4f}"
    # the cache really stores int8 data (+ f32 scales)
    assert isinstance(kc_q, tuple) and kc_q[0].dtype == jnp.int8
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        llama_decode_factory(model, max_len=32, kv_cache_dtype="fp4")


def test_int8_weights_close_to_fp():
    """weight_dtype='int8': per-channel weight quant + dynamic activation
    quant keep greedy decode on-sequence; weights really stored int8."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=97, hidden=64, layers=2, heads=4,
                           kv_heads=2)
    paddle.seed(12)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen_fp = llama_decode_factory(model, max_len=32)
    gen_w8 = llama_decode_factory(model, max_len=32, weight_dtype="int8")
    prompt = np.asarray(
        np.random.default_rng(1).integers(0, 97, (2, 6)), np.int32)
    fp = np.asarray(gen_fp(prompt, max_new_tokens=8))
    w8 = np.asarray(gen_w8(prompt, max_new_tokens=8))
    assert (fp[:, 6:] == w8[:, 6:]).mean() > 0.8, (fp, w8)
    # and the two quantizations compose
    gen_both = llama_decode_factory(model, max_len=32,
                                    kv_cache_dtype="int8",
                                    weight_dtype="int8")
    b8 = np.asarray(gen_both(prompt, max_new_tokens=8))
    # stacked quantizations: one early flip cascades autoregressively,
    # so assert a short pre-divergence prefix instead of total agreement
    assert (fp[:, 6:9] == b8[:, 6:9]).all(), (fp, b8)
    with pytest.raises(ValueError, match="weight_dtype"):
        llama_decode_factory(model, max_len=32, weight_dtype="fp8")


def test_int8_kv_cache_with_rolling_window():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    cfg = LlamaConfig.tiny(vocab=61, hidden=32, layers=1, heads=2,
                           kv_heads=2)
    cfg.sliding_window = 8
    paddle.seed(13)
    model = LlamaForCausalLM(cfg)
    model.eval()
    gen_fp = llama_decode_factory(model, max_len=16)
    gen_q = llama_decode_factory(model, max_len=16, kv_cache_dtype="int8")
    prompt = np.ones((1, 12), np.int32)  # rolled prefill (S0 > window)
    fp = np.asarray(gen_fp(prompt, max_new_tokens=10))
    q8 = np.asarray(gen_q(prompt, max_new_tokens=10))
    assert fp.shape == q8.shape == (1, 22)
    assert (fp[:, 12:] == q8[:, 12:]).mean() > 0.7, (fp, q8)


def test_speculative_decode_exactly_matches_target_greedy():
    """Greedy speculative decoding must produce EXACTLY the target
    model's greedy generation (speculation changes latency, not content)
    while running far fewer target steps than tokens generated."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_decode_factory, llama_speculative_decode_factory)
    paddle.seed(31)
    target = LlamaForCausalLM(LlamaConfig.tiny(
        vocab=97, hidden=64, layers=3, heads=4, kv_heads=2))
    target.eval()
    paddle.seed(32)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        vocab=97, hidden=32, layers=1, heads=2, kv_heads=2))
    draft.eval()
    prompt = np.asarray(
        np.random.default_rng(2).integers(0, 97, (1, 6)), np.int32)
    oracle = np.asarray(llama_decode_factory(target, max_len=64)(
        prompt, max_new_tokens=24))
    spec = llama_speculative_decode_factory(target, draft, max_len=64,
                                            n_draft=4)
    got = spec(prompt, max_new_tokens=24)
    np.testing.assert_array_equal(got, oracle)
    assert spec.last_stats["tokens"] == 24

    # with the TARGET as its own draft every proposal is accepted: this
    # exercises the full-acceptance path (the unconsumed last draft is
    # re-fed, so the draft cache never holds a hole) and the speedup
    # accounting must show ~5 tokens per target step
    spec2 = llama_speculative_decode_factory(target, target, max_len=64,
                                             n_draft=4)
    got2 = spec2(prompt, max_new_tokens=24)
    np.testing.assert_array_equal(got2, oracle)
    stats = spec2.last_stats
    assert stats["target_steps"] < 24 // 3, stats  # ~24/5 rounds + 1


def test_dense_compiled_greedy_matches_python_loop():
    """gen.compiled (the one-program greedy loop serving routes uniform
    batches to) must be byte-identical to generate() across the plain,
    int8-cache and rolling-window cache variants, and honor the
    zero-budget edge."""
    import jax.numpy as jnp
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import llama_decode_factory
    prompt = np.asarray(
        np.random.default_rng(2).integers(1, 97, (2, 6)), np.int32)
    for label, cfg_kw, fac_kw in [
            ("plain", {}, {}),
            ("int8_cache", {}, {"kv_cache_dtype": "int8"}),
            ("rolling", {"sliding_window": 8}, {})]:
        paddle.seed(31)
        cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                               kv_heads=2)
        for k, v in cfg_kw.items():
            setattr(cfg, k, v)
        model = LlamaForCausalLM(cfg)
        model.eval()
        gen = llama_decode_factory(model, max_len=48, **fac_kw)
        for new in (1, 16):
            a = np.asarray(gen(jnp.asarray(prompt), max_new_tokens=new))
            b = gen.compiled(prompt, new)
            np.testing.assert_array_equal(a, b, err_msg=f"{label}/{new}")
        np.testing.assert_array_equal(gen.compiled(prompt, 0), prompt,
                                      err_msg=f"{label}/zero-budget")


def test_speculative_compiled_loop_matches_python_loop():
    """The one-program speculative loop (generate.compiled — the whole
    draft/verify/accept cycle inside lax.while_loop) must produce
    byte-identical output to the per-round python loop AND to plain
    greedy, for both a perfect draft and a disagreeing draft."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_decode_factory, llama_speculative_decode_factory)
    paddle.seed(31)
    target = LlamaForCausalLM(LlamaConfig.tiny(
        vocab=97, hidden=64, layers=3, heads=4, kv_heads=2))
    target.eval()
    paddle.seed(32)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        vocab=97, hidden=32, layers=1, heads=2, kv_heads=2))
    draft.eval()
    prompt = np.asarray(
        np.random.default_rng(2).integers(0, 97, (1, 6)), np.int32)
    oracle = np.asarray(llama_decode_factory(target, max_len=64)(
        prompt, max_new_tokens=20))
    for d in (draft, target):
        spec = llama_speculative_decode_factory(target, d, max_len=64,
                                                n_draft=4)
        got_py = spec(prompt, max_new_tokens=20)
        got_c = spec.compiled(prompt, max_new_tokens=20)
        np.testing.assert_array_equal(got_c, got_py)
        np.testing.assert_array_equal(got_c, oracle)
        assert spec.compiled.last_stats["rounds"] >= 1
    # perfect draft: compiled loop must also show the ~k+1-per-round
    # acceptance in its stats
    assert spec.compiled.last_stats["target_steps"] < 20 // 3


def test_speculative_decode_rejects_bad_configs():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_speculative_decode_factory)
    t = LlamaForCausalLM(LlamaConfig.tiny(vocab=97))
    d = LlamaForCausalLM(LlamaConfig.tiny(vocab=61))
    with pytest.raises(ValueError, match="vocabulary"):
        llama_speculative_decode_factory(t, d)
    cfg = LlamaConfig.tiny(vocab=97)
    cfg.sliding_window = 8
    w = LlamaForCausalLM(cfg)
    t2 = LlamaForCausalLM(LlamaConfig.tiny(vocab=97))
    with pytest.raises(ValueError, match="sliding_window"):
        llama_speculative_decode_factory(t2, w)
