"""Paged KV-cache decode attention (ops/pallas/paged_attention.py):
kernel-vs-oracle parity in interpret mode + the PagedKVCache pool
bookkeeping a serving loop relies on."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.paged_attention import (PagedKVCache,
                                                   paged_attention,
                                                   paged_attention_reference)


def _setup(rng, B=2, Hq=4, Hkv=2, D=16, P=9, page_size=8, n_pages=3):
    q = jnp.asarray(rng.normal(0, 1, (B, Hq, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(0, 1, (Hkv, P, page_size, D)),
                     jnp.float32)
    vp = jnp.asarray(rng.normal(0, 1, (Hkv, P, page_size, D)),
                     jnp.float32)
    pt = jnp.asarray(rng.choice(np.arange(1, P), (B, n_pages),
                                replace=False), jnp.int32)
    return q, kp, vp, pt


def test_kernel_matches_oracle_ragged_lengths():
    rng = np.random.default_rng(0)
    q, kp, vp, pt = _setup(rng)
    # ragged: mid-page end, exact page edge
    sl = jnp.asarray([13, 16], jnp.int32)
    got = paged_attention(q, kp, vp, pt, sl)
    want = paged_attention_reference(q, kp, vp, pt, sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_kernel_jits_and_single_token():
    rng = np.random.default_rng(1)
    q, kp, vp, pt = _setup(rng)
    sl = jnp.asarray([1, 5], jnp.int32)
    f = jax.jit(paged_attention)
    got = f(q, kp, vp, pt, sl)
    want = paged_attention_reference(q, kp, vp, pt, sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_mqa_group():
    rng = np.random.default_rng(2)
    q, kp, vp, pt = _setup(rng, Hq=6, Hkv=1)
    sl = jnp.asarray([20, 9], jnp.int32)
    got = paged_attention(q, kp, vp, pt, sl)
    want = paged_attention_reference(q, kp, vp, pt, sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_cache_serving_loop():
    """Pool bookkeeping end-to-end: prefill two sequences, decode-append,
    free one, reuse its pages for a third — attention over the pool
    matches a dense oracle at every step."""
    rng = np.random.default_rng(3)
    Hkv, D, ps = 2, 16, 8
    cache = PagedKVCache(n_pages=8, page_size=ps, kv_heads=Hkv,
                         head_dim=D, dtype=jnp.float32)

    dense = {}

    def append(sid, T):
        k = jnp.asarray(rng.normal(0, 1, (Hkv, T, D)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (Hkv, T, D)), jnp.float32)
        cache.write(sid, k, v)
        pk, pv = dense.get(sid, (jnp.zeros((Hkv, 0, D)),
                                 jnp.zeros((Hkv, 0, D))))
        dense[sid] = (jnp.concatenate([pk, k], 1),
                      jnp.concatenate([pv, v], 1))

    append("a", 11)   # 2 pages, mid-page end
    append("b", 8)    # exactly 1 page
    append("a", 3)    # decode appends continue page 2

    q = jnp.asarray(rng.normal(0, 1, (2, 4, D)), jnp.float32)
    pt, sl = cache.batch_views(["a", "b"])
    got = paged_attention(q, cache.k_pages, cache.v_pages, pt, sl)
    for i, sid in enumerate(["a", "b"]):
        k, v = dense[sid]
        G = 4 // Hkv
        qg = q[i].reshape(Hkv, G, D)
        s = jnp.einsum("hgd,hsd->hgs", qg, k) / np.sqrt(D)
        want = jnp.einsum("hgs,hsd->hgd", jax.nn.softmax(s, -1),
                          v).reshape(4, D)
        np.testing.assert_allclose(np.asarray(got[i]), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    # free + reuse
    pages_a = set(cache.tables["a"])
    cache.free("a")
    append("c", 30)   # needs 4 pages; must reuse a's
    assert pages_a & set(cache.tables["c"])
    with pytest.raises(MemoryError):
        append("c", 100)


def test_pool_exhaustion_and_padding_page():
    cache = PagedKVCache(n_pages=3, page_size=4, kv_heads=1, head_dim=8)
    # page 0 is reserved for table padding: only 2 usable pages
    cache.allocate("s", 8)
    with pytest.raises(MemoryError):
        cache.allocate("s", 12)


def test_int8_pool_matches_dequant_oracle():
    """int8 pages + per-slot scales: the kernel's in-VMEM dequant must
    match the dense oracle run over the dequantized pool."""
    rng = np.random.default_rng(7)
    B, Hq, Hkv, D, P, ps, n = 2, 4, 2, 16, 9, 8, 3
    q = jnp.asarray(rng.normal(0, 1, (B, Hq, D)), jnp.float32)
    kf = rng.normal(0, 1, (Hkv, P, ps, D)).astype(np.float32)
    vf = rng.normal(0, 1, (Hkv, P, ps, D)).astype(np.float32)

    def quant(x):
        scale = np.maximum(np.abs(x).max(-1), 1e-8) / 127.0
        qd = np.clip(np.round(x / scale[..., None]), -127, 127)
        return qd.astype(np.int8), scale.astype(np.float32)

    kq, ks = quant(kf)
    vq, vs = quant(vf)
    pt = jnp.asarray(rng.choice(np.arange(1, P), (B, n), replace=False),
                     jnp.int32)
    sl = jnp.asarray([13, 16], jnp.int32)
    got = paged_attention(q, jnp.asarray(kq), jnp.asarray(vq), pt, sl,
                          k_scales=jnp.asarray(ks),
                          v_scales=jnp.asarray(vs))
    want = paged_attention_reference(
        q, jnp.asarray(kq.astype(np.float32) * ks[..., None]),
        jnp.asarray(vq.astype(np.float32) * vs[..., None]), pt, sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="BOTH"):
        paged_attention(q, jnp.asarray(kq), jnp.asarray(vq), pt, sl,
                        k_scales=jnp.asarray(ks))


def test_prefill_kernel_matches_dense_gather():
    """paged_prefill_attention (chunk queries x pages, absolute-position
    causal) vs the dense gather+softmax oracle, fp and int8 pools."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_prefill_attention)
    rng = np.random.default_rng(11)
    B, Hq, Hkv, C, D, P, ps, W = 2, 4, 2, 8, 16, 9, 8, 3
    start = 8  # second page
    q = jnp.asarray(rng.normal(0, 1, (B, Hq, C, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(0, 1, (Hkv, P, ps, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(0, 1, (Hkv, P, ps, D)), jnp.float32)
    pt = jnp.asarray(rng.choice(np.arange(1, P), (B, W), replace=False),
                     jnp.int32)
    sl = jnp.asarray([start + C, start + 5], jnp.int32)

    got = paged_prefill_attention(q, kp, vp, pt, sl, start)

    # dense oracle
    S = W * ps
    k = jnp.swapaxes(kp[:, pt], 0, 1).reshape(B, Hkv, S, D)
    v = jnp.swapaxes(vp[:, pt], 0, 1).reshape(B, Hkv, S, D)
    G = Hq // Hkv
    qg = q.reshape(B, Hkv, G, C, D)
    s = jnp.einsum("bhgcd,bhsd->bhgcs", qg, k) / np.sqrt(D)
    col = jnp.arange(S)[None, None, None, None, :]
    row = start + jnp.arange(C)[None, None, None, :, None]
    mask = (col <= row) & (col < jnp.asarray(sl)[:, None, None, None,
                                                 None])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    want = jnp.einsum("bhgcs,bhsd->bhgcd", p, v).reshape(B, Hq, C, D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # int8 pool path agrees with its own dequantized oracle
    def quant(x):
        x = np.asarray(x)
        sc = np.maximum(np.abs(x).max(-1), 1e-8) / 127.0
        qd = np.clip(np.round(x / sc[..., None]), -127, 127)
        return jnp.asarray(qd.astype(np.int8)), jnp.asarray(
            sc.astype(np.float32))
    kq, ks = quant(kp)
    vq, vs = quant(vp)
    got8 = paged_prefill_attention(q, kq, vq, pt, sl, start,
                                   k_scales=ks, v_scales=vs)
    want8 = paged_prefill_attention(
        q, kq.astype(jnp.float32) * ks[..., None],
        vq.astype(jnp.float32) * vs[..., None], pt, sl, start)
    np.testing.assert_allclose(np.asarray(got8), np.asarray(want8),
                               rtol=2e-5, atol=2e-5)


def test_prefix_cache_child_keys_die_with_parent():
    """Recycled page ids must never resurrect prefix chains. Under
    retention, freeing the last holder PARKS published pages in the
    evictable LRU (keys live, chains still matchable); only EVICTION
    recycles an id, and it takes every key chained through the page
    with it (the wrong-context-KV hazard) — children always before
    parents. A partially-failed admit recovers via free() + retry."""
    ps = 4
    cache = PagedKVCache(n_pages=8, page_size=ps, kv_heads=1, head_dim=8)
    X = list(range(10, 10 + ps))
    Y = list(range(20, 20 + ps))
    Z = list(range(30, 30 + ps))

    # A publishes X+Y; B publishes X+Z uncached-overlapping (collides on X)
    assert cache.acquire_prefix("A", X + Y) == 0
    cache.allocate("A", 2 * ps)
    cache.register_prefix("A", X + Y)
    assert cache.acquire_prefix("B", X + Z) == ps  # shares A's X page
    cache.allocate("B", 2 * ps)
    cache.register_prefix("B", X + Z)
    pX = cache.tables["A"][0]
    assert cache.tables["B"][0] == pX and cache._refs[pX] == 2

    # free both: the published pages are RETAINED (evictable), not
    # dropped — both chains still match for free
    cache.free("A")
    cache.free("B")
    assert pX in cache._evictable and pX not in cache._free
    assert cache.match_prefix(X + Y) == 2 * ps
    assert cache.match_prefix(X + Z) == 2 * ps

    # allocation pressure reclaims leaf-first: 7 usable pages, 3
    # evictable (X, Y-child, Z-child); taking 6 evicts the two LEAVES,
    # X survives as the most valuable (still-parenting) page
    cache.allocate("C", 6 * ps)
    assert cache.match_prefix(X + Y) == ps  # children gone...
    assert cache.match_prefix(X + Z) == ps
    assert cache.match_prefix(X) == ps      # ...parent still cached
    cache.free("C")

    # full pressure recycles X too; a new sequence publishing W under
    # X's recycled id must NOT make stale (X -> Y/Z) chains matchable
    cache.allocate("C", 7 * ps)
    assert cache.match_prefix(X) == 0
    cache.free("C")
    W = list(range(40, 40 + ps))
    assert cache.acquire_prefix("C", W) == 0
    cache.allocate("C", ps)
    cache.register_prefix("C", W)
    assert cache.acquire_prefix("D", W + Z) == ps  # only W matches
    # lengths bookkeeping: write() appends AFTER the cached prefix
    assert cache.lengths["D"] == ps

    # recovery contract: failed allocate -> free -> retry works
    cache.free("D")
    assert cache.acquire_prefix("D", W + Z) == ps
    with pytest.raises(MemoryError):
        cache.allocate("D", 100 * ps)
    cache.free("D")
    assert cache.acquire_prefix("D", W + Z) == ps  # no assert, no leak
    cache.free("D")
    # census invariant held throughout
    s = cache.cache_stats()
    assert s["resident_pages"] + s["evictable_pages"] \
        + s["free_pages"] == s["n_pages"]
