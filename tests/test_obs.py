"""paddle_tpu.obs: metrics registry + tracer units, the jit
program-cache stats satellite, the profiler export-name fix, and the
bench_gate obs family (synthetic rows through the real subprocess).
"""
import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace as obs_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- metrics registry -----------------------------------------------------
def test_counter_gauge_histogram_semantics():
    r = obs_metrics.MetricsRegistry()
    c = r.counter("reqs_total", "requests", tenant="a")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotonic
    # same (name, labels) -> the same child; new labels -> a sibling
    assert r.counter("reqs_total", tenant="a") is c
    assert r.counter("reqs_total", tenant="b") is not c
    g = r.gauge("depth")
    g.set(4)
    g.dec()
    assert g.value == 3
    h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3 and h.sum == pytest.approx(5.55)
    assert h.cumulative() == [(0.1, 1), (1.0, 2), (float("inf"), 3)]
    # a name cannot change type
    with pytest.raises(ValueError):
        r.gauge("reqs_total")


def test_registry_disable_is_a_kill_switch():
    r = obs_metrics.MetricsRegistry()
    c = r.counter("c_total")
    h = r.histogram("h_seconds", buckets=(1.0,))
    g = r.gauge("g")
    r.disable()
    c.inc(5)
    h.observe(0.5)
    g.set(9)
    assert c.value == 0 and h.count == 0 and g.value == 0
    r.enable()
    c.inc()
    assert c.value == 1


def test_prometheus_exposition_format():
    r = obs_metrics.MetricsRegistry()
    r.counter("a_total", "help text", rule="x").inc(2)
    r.gauge("b").set(1.5)
    r.histogram("c_seconds", buckets=(0.5,)).observe(0.1)
    text = r.expose_text()
    assert "# HELP a_total help text" in text
    assert "# TYPE a_total counter" in text
    assert 'a_total{rule="x"} 2' in text
    assert "# TYPE b gauge" in text and "b 1.5" in text
    assert 'c_seconds_bucket{le="0.5"} 1' in text
    assert 'c_seconds_bucket{le="+Inf"} 1' in text
    assert "c_seconds_sum 0.1" in text and "c_seconds_count 1" in text
    # deterministic: families sorted by name
    names = [ln.split("# TYPE ")[1].split()[0]
             for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert names == sorted(names)


def _scrape_parse(text: str) -> dict:
    """A minimal Prometheus text-format scrape parser (the consumer's
    view): every sample line must be `name{labels} value` with a
    preceding # TYPE for its family. Returns
    {family: {"type":..., "samples": [(name, {labels}, value)]}}."""
    import re
    fams: dict = {}
    cur = None
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            continue
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split(" ", 3)
            cur = name
            fams[name] = {"type": kind, "samples": []}
            continue
        m = re.fullmatch(
            r'([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="[^"]*",?)*)\})?'
            r' (\S+)', ln)
        assert m, f"unparseable exposition line: {ln!r}"
        name, labels, value = m.group(1), m.group(2), m.group(3)
        lab = {}
        if labels:
            for item in filter(None, labels.split('",')):
                k, v = item.split('="', 1)
                lab[k] = v.rstrip('"')
        base = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in fams:
                base = name[: -len(suf)]
        assert base in fams, f"sample {name} precedes its # TYPE"
        fams[base]["samples"].append((name, lab, float(value)))
    return fams


def test_histogram_exposition_scrape_conformance():
    """Satellite: the fixed-bucket histogram exposition against the
    rules a Prometheus scrape enforces — cumulative buckets ending in
    an explicit le="+Inf" equal to _count, monotone non-decreasing
    counts, and _sum/_count lines per child."""
    r = obs_metrics.MetricsRegistry()
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0, 5.0),
                    backend="paged")
    for v in (0.05, 0.5, 0.7, 3.0, 99.0):  # 99.0 beyond every bound
        h.observe(v)
    fams = _scrape_parse(r.expose_text())
    fam = fams["lat_seconds"]
    assert fam["type"] == "histogram"
    buckets = [(lab["le"], val) for name, lab, val in fam["samples"]
               if name == "lat_seconds_bucket"]
    # exposition order IS ascending le with +Inf last
    assert [le for le, _ in buckets] == ["0.1", "1", "5", "+Inf"]
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)          # cumulative-monotone
    assert counts == [1.0, 3.0, 4.0, 5.0]
    cnt = [val for name, lab, val in fam["samples"]
           if name == "lat_seconds_count"]
    sm = [val for name, lab, val in fam["samples"]
          if name == "lat_seconds_sum"]
    assert cnt == [5.0] and counts[-1] == cnt[0]  # +Inf == _count
    assert sm[0] == pytest.approx(103.25)
    # the child's own labels ride every bucket line
    assert all(lab.get("backend") == "paged"
               for name, lab, _ in fam["samples"])


def test_histogram_exposition_golden_text():
    """The exact exposition bytes, frozen: a scrape consumer diff
    reads format drift here before a dashboard does."""
    r = obs_metrics.MetricsRegistry()
    h = r.histogram("q_seconds", "queue wait", buckets=(0.25, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    h.observe(9.0)
    r.counter("n_total", "count", rule="x").inc(3)
    golden = (
        "# HELP n_total count\n"
        "# TYPE n_total counter\n"
        'n_total{rule="x"} 3\n'
        "# HELP q_seconds queue wait\n"
        "# TYPE q_seconds histogram\n"
        'q_seconds_bucket{le="0.25"} 1\n'
        'q_seconds_bucket{le="2"} 2\n'
        'q_seconds_bucket{le="+Inf"} 3\n'
        "q_seconds_sum 10.1\n"
        "q_seconds_count 3\n")
    assert r.expose_text() == golden


def test_jsonl_snapshot_round_trip(tmp_path):
    r = obs_metrics.MetricsRegistry()
    r.counter("n_total").inc(7)
    p = tmp_path / "snap.jsonl"
    r.write_jsonl(str(p), run="unit")
    r.counter("n_total").inc()
    r.write_jsonl(str(p), run="unit")
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["metrics"]["n_total"] == 7
    assert lines[1]["metrics"]["n_total"] == 8
    assert all(ln["run"] == "unit" and "ts" in ln for ln in lines)


# --- tracer ---------------------------------------------------------------
def test_tracer_chrome_export_schema(tmp_path):
    t = obs_trace.Tracer(clock=lambda: 2.0)
    t.add_span("work", 1.0, 0.5, track="engine", rid="A")
    with t.span("inner", track="engine"):
        pass
    t.instant("mark", t=1.25, track="engine")
    t.async_begin("request", "A", t=0.0, track="tenant/x")
    t.async_end("request", "A", t=3.0, track="tenant/x")
    t.counter("depth", 2, t=0.5)
    p = tmp_path / "tr.json"
    t.export(str(p))
    d = json.loads(p.read_text())
    evts = d["traceEvents"]
    assert isinstance(evts, list) and evts
    # every event chrome-well-formed; ts in MICROseconds
    for e in evts:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] != "M":
            assert "ts" in e
    span = next(e for e in evts if e["name"] == "work")
    assert span["ts"] == 1e6 and span["dur"] == 0.5e6
    # track metadata present and bound to the tids used
    tracks = {e["tid"]: e["args"]["name"] for e in evts
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "engine" in tracks.values()
    assert tracks[span["tid"]] == "engine"
    # async pair balanced
    assert sum(1 for e in evts if e["ph"] == "b") == \
        sum(1 for e in evts if e["ph"] == "e") == 1


@pytest.mark.parametrize("virtual", [True, False])
def test_counter_series_flushed_on_export(virtual, tmp_path):
    """Satellite: counter samples survive export() even when the LAST
    sample precedes the final span — a counter series must never be
    dropped or reordered relative to its record order just because a
    later span closed after it. Both clock types: a virtual fixed
    clock (explicit timestamps) and the wall clock (tracer-stamped)."""
    if virtual:
        t = obs_trace.Tracer(clock=lambda: 10.0)
        stamps = {"t": 1.0}
        t.counter("queue_depth", 1, t=0.5)
        t.add_span("turn0", 0.6, 0.2, track="engine")
        t.counter("queue_depth", 3, t=1.0)
        # the final span STARTS after the last counter sample and is
        # recorded last
        t.add_span("turn1", 2.0, 4.0, track="engine")
    else:
        t = obs_trace.Tracer()  # wall clock
        t.counter("queue_depth", 1)
        t.add_span("turn0", t.now(), 0.0, track="engine")
        t.counter("queue_depth", 3)
        t.add_span("turn1", t.now(), 0.0, track="engine")
    p = tmp_path / "tr.json"
    t.export(str(p))
    evts = json.loads(p.read_text())["traceEvents"]
    ctrs = [e for e in evts if e.get("ph") == "C"]
    spans = [e for e in evts if e.get("ph") == "X"]
    # every sample exported, values in record order, none coalesced
    assert [e["args"]["value"] for e in ctrs] == [1, 3]
    assert [e["name"] for e in spans] == ["turn0", "turn1"]
    # the last counter's timestamp precedes the final span's close;
    # export preserved the samples anyway (no tail-flush loss)
    last_span = spans[-1]
    assert ctrs[-1]["ts"] <= last_span["ts"] + last_span["dur"]
    # counters land on their own track with metadata bound to it
    tracks = {e["tid"]: e["args"]["name"] for e in evts
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert all(tracks[e["tid"]] == "counters" for e in ctrs)


def test_trace_scope_tags_trace_id():
    t = obs_trace.Tracer(clock=lambda: 0.0)
    with obs_trace.trace_scope("req-1"):
        t.add_span("prefill", 0.0, 1.0)
        assert obs_trace.get_trace_id() == "req-1"
    t.add_span("decode", 1.0, 1.0)
    assert obs_trace.get_trace_id() is None
    tagged = [e for e in t.events if e["name"] == "prefill"]
    untagged = [e for e in t.events if e["name"] == "decode"]
    assert tagged[0]["args"]["trace_id"] == "req-1"
    assert "trace_id" not in untagged[0]["args"]


def test_tracer_clear_drops_tracks_too():
    """A reused tracer (the engine clears at each run start) must not
    export ghost tracks from a previous run."""
    t = obs_trace.Tracer(clock=lambda: 0.0)
    t.add_span("w", 0.0, 1.0, track="tenant/old")
    t.clear()
    t.add_span("w", 0.0, 1.0, track="tenant/new")
    tracks = {e["args"]["name"]
              for e in t.to_chrome()["traceEvents"]
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert tracks == {"tenant/new"}


def test_active_tracer_install_restore():
    assert obs_trace.active() is None
    t1, t2 = obs_trace.Tracer(), obs_trace.Tracer()
    with obs_trace.use(t1):
        assert obs_trace.active() is t1
        with obs_trace.use(t2):
            assert obs_trace.active() is t2
        assert obs_trace.active() is t1
        with obs_trace.use(None):  # None = no-op, not a clear
            assert obs_trace.active() is t1
    assert obs_trace.active() is None


# --- jit program-cache stats (satellite) ----------------------------------
def test_jit_cache_stats_public_api():
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def f(x):
        return x * 2 + 1

    before = obs_metrics.REGISTRY.counter("jit_cache_hits_total").value
    x = paddle.ones([2, 3])
    f(x)           # miss + compile
    f(x)           # hit
    f(x * 0)       # hit (same signature)
    f(paddle.ones([4, 3]))  # miss + compile (new shape)
    st = f.cache_stats()
    assert st["hits"] == 2 and st["misses"] == 2
    assert st["compiles"] == 2
    assert st["last_compile_s"] is not None and st["last_compile_s"] > 0
    # the legacy private dict is the SAME ledger (back-compat)
    assert f._cache_info["hits"] == 2
    # obs counters moved with it
    after = obs_metrics.REGISTRY.counter("jit_cache_hits_total").value
    assert after - before == 2


def test_jit_compile_span_reaches_active_tracer():
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def g(x):
        return x + 1

    t = obs_trace.Tracer(clock=lambda: 0.0)
    with obs_trace.use(t):
        g(paddle.ones([5]))
    compiles = [e for e in t.events if e["name"] == "jit.compile"]
    assert len(compiles) == 1
    assert compiles[0]["args"]["wall_s"] > 0


# --- profiler export filename (satellite) ---------------------------------
def test_export_chrome_tracing_deterministic_name(tmp_path):
    from paddle_tpu import profiler

    prof = profiler.Profiler(timer_only=True)
    prof.start()
    handler = profiler.export_chrome_tracing(
        str(tmp_path), worker_name="w0", timestamp=False)
    handler(prof)
    assert (tmp_path / "w0.json").exists()  # exactly, no suffix
    # default keeps the historical wall-stamp suffix
    handler2 = profiler.export_chrome_tracing(str(tmp_path),
                                              worker_name="w1")
    handler2(prof)
    stamped = [p.name for p in tmp_path.iterdir()
               if p.name.startswith("w1_")]
    assert len(stamped) == 1 and stamped[0].endswith(".json")
    prof.stop()


# --- bench_gate obs family ------------------------------------------------
def _run_obs_gate(rows):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "obs", "-"], input=rows, capture_output=True, text=True,
        timeout=60, cwd=REPO)
    return r.returncode, [json.loads(ln) for ln in
                          r.stdout.strip().splitlines()]


def _ovh_row(noobs, off, **kw):
    return json.dumps({"bench": "obs_overhead", "noobs_wall_s": noobs,
                       "off_wall_s": off, "on_wall_s": off * 1.1,
                       "tokens_match": True, "device": "cpu", **kw})


def _tr_row(**kw):
    d = {"bench": "obs_trace", "events": 100, "roots_open": 4,
         "roots_closed": 4, "unclosed_roots": [], "path": "t.json"}
    d.update(kw)
    return json.dumps(d)


def test_bench_gate_obs_overhead():
    rc, recs = _run_obs_gate(_ovh_row(1.0, 1.01) + "\n")
    assert rc == 0 and recs[-1]["gate"] == "pass"
    # > 2% tracing-off tax FAILs with the reason named
    rc, recs = _run_obs_gate(_ovh_row(1.0, 1.05) + "\n")
    assert rc == 1 and recs[-1]["gate"] == "FAIL"
    assert "not free" in recs[-1]["reason"]
    # diverging token counts across arms FAIL (behavior, not cost)
    rc, recs = _run_obs_gate(
        _ovh_row(1.0, 1.0, tokens_match=False) + "\n")
    assert rc == 1 and "DIVERGING" in recs[-1]["reason"]
    # no wall measurements FAIL gracefully
    rc, recs = _run_obs_gate(
        json.dumps({"bench": "obs_overhead"}) + "\n")
    assert rc == 1 and "wall" in recs[-1]["reason"]


def test_bench_gate_obs_trace_and_combined():
    rc, recs = _run_obs_gate(_tr_row() + "\n")
    assert rc == 0 and recs[-1]["gate"] == "pass"
    rc, recs = _run_obs_gate(
        _tr_row(roots_closed=3, unclosed_roots=["q1"]) + "\n")
    assert rc == 1 and "never closed" in recs[-1]["reason"]
    rc, recs = _run_obs_gate(_tr_row(events=0) + "\n")
    assert rc == 1 and "zero events" in recs[-1]["reason"]
    # no obs row at all -> graceful FAIL record, not a traceback
    rc, recs = _run_obs_gate(json.dumps({"bench": "other"}) + "\n")
    assert rc == 1 and recs[-1]["gate"] == "FAIL"
    assert "obs_overhead" in recs[-1]["reason"]
    # both families: combined verdict is the LAST record; a passing
    # trace row must not mask a failed overhead gate
    rc, recs = _run_obs_gate(
        _ovh_row(1.0, 1.5) + "\n" + _tr_row() + "\n")
    assert rc == 1
    assert recs[-1]["combined"] is True and recs[-1]["gate"] == "FAIL"
    assert recs[-1]["overhead_gate"] == "FAIL"
    assert recs[-1]["trace_gate"] == "pass"


def test_bench_gate_obs_empty_input():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "obs", "-"], input="", capture_output=True, text=True,
        timeout=60, cwd=REPO)
    assert r.returncode == 1
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["gate"] == "FAIL"  # graceful record, never a traceback
