"""Automatic prefix-cache retention + cache-aware scheduling (PR 5).

Pool level: evictable-LRU retention semantics (revival on hit,
leaf-first eviction order, LRU order among leaves, exhaustion only
when the LRU is empty, root-parent ``_children`` bookkeeping and the
page-id-recycling regression). Engine level: automatic acquisition
without ``prefix_group`` tags, the leak-proof failed-allocate
rollback, per-chunk fixed-clock pricing, report/publish surfacing,
determinism with caching on and off. Scheduler level: cache-aware
deadline-feasibility pricing. Plus the ``serving_prefix`` bench-gate
contract (no model needed for those)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.paged_attention import PagedKVCache
from paddle_tpu.serving import (Request, ServiceEstimator, ServingEngine,
                                synthesize_recurring_prefix_trace)

PS = 4


def _toks(base, n=PS):
    return list(range(base, base + n))


def _census_ok(cache):
    s = cache.cache_stats()
    assert s["resident_pages"] + s["evictable_pages"] \
        + s["free_pages"] == s["n_pages"], s
    return s


# --- pool-level retention ---------------------------------------------------

def test_evictable_revival_on_hit():
    """A published chain freed by its last holder parks evictable and
    a later identical prefix revives it wholesale — full hit, zero
    prefill, pages back to resident."""
    c = PagedKVCache(n_pages=8, page_size=PS, kv_heads=1, head_dim=8)
    X, Y = _toks(10), _toks(20)
    c.acquire_prefix("A", X + Y)
    c.allocate("A", 2 * PS)
    c.register_prefix("A", X + Y)
    c.free("A")
    s = _census_ok(c)
    assert s["evictable_pages"] == 2 and s["resident_pages"] == 0
    assert c.match_prefix(X + Y) == 2 * PS  # probe: still matchable
    assert c.acquire_prefix("B", X + Y) == 2 * PS
    s = _census_ok(c)
    assert s["evictable_pages"] == 0 and s["resident_pages"] == 2
    assert all(c._refs[p] == 1 for p in c.tables["B"])
    c.free("B")
    _census_ok(c)


def test_leaf_first_eviction_order():
    """Pressure on a parked chain reclaims the DEEPEST page first: a
    parent never dies before its children, so a surviving parent's key
    can never chain to a recycled child id."""
    c = PagedKVCache(n_pages=8, page_size=PS, kv_heads=1, head_dim=8)
    X, Y, Z = _toks(10), _toks(20), _toks(30)
    c.acquire_prefix("A", X + Y + Z)
    c.allocate("A", 3 * PS)
    c.register_prefix("A", X + Y + Z)
    c.free("A")  # chain X -> Y -> Z parked, LRU holds all three
    c.allocate("B", 5 * PS)  # 4 free + needs 1 evicted
    assert c.match_prefix(X + Y + Z) == 2 * PS  # Z (leaf) died first
    c.free("B")
    c.allocate("B", 6 * PS)
    assert c.match_prefix(X + Y) == PS          # then Y
    assert c.match_prefix(X) == PS              # X still cached
    c.free("B")
    c.allocate("B", 7 * PS)
    assert c.match_prefix(X) == 0               # finally the root page
    c.free("B")
    _census_ok(c)


def test_lru_order_among_independent_leaves():
    """Two unrelated single-page prefixes freed in order: pressure
    reclaims the LEAST recently parked first, and a hit refreshes a
    page's standing by making it resident again."""
    c = PagedKVCache(n_pages=6, page_size=PS, kv_heads=1, head_dim=8)
    A, B = _toks(10), _toks(20)
    for sid, toks in (("a", A), ("b", B)):
        c.acquire_prefix(sid, toks)
        c.allocate(sid, PS)
        c.register_prefix(sid, toks)
    c.free("a")   # a parked first -> LRU victim
    c.free("b")
    c.allocate("x", 4 * PS)  # 3 free + 1 evicted
    assert c.match_prefix(A) == 0 and c.match_prefix(B) == PS
    c.free("x")
    # a revival makes the page RESIDENT again — pressure that would
    # have reclaimed it must take free pages instead
    assert c.acquire_prefix("b2", B) == PS
    c.allocate("x", 4 * PS)
    assert c.match_prefix(B) == PS  # b2 still holds it
    c.free("x")
    c.free("b2")
    _census_ok(c)


def test_exhaustion_memoryerror_only_when_lru_empty():
    """allocate must consume the whole evictable pool before raising —
    and a failing allocate mutates nothing (clean requeue)."""
    c = PagedKVCache(n_pages=6, page_size=PS, kv_heads=1, head_dim=8)
    A = _toks(10)
    c.acquire_prefix("a", A + _toks(20))
    c.allocate("a", 2 * PS)
    c.register_prefix("a", A + _toks(20))
    c.free("a")
    s0 = _census_ok(c)
    assert s0["evictable_pages"] == 2
    with pytest.raises(MemoryError):
        c.allocate("x", 6 * PS)  # 5 usable total
    assert _census_ok(c) == s0  # nothing moved on the failed path
    c.allocate("x", 5 * PS)      # == free + evictable: succeeds
    s = _census_ok(c)
    assert s["evictable_pages"] == 0 and s["evictions"] == 2
    c.free("x")


def test_root_children_bookkeeping_and_recycling_regression():
    """Root-parent (parent == 0) keys are tracked in ``_children[0]``
    (the expression-form bug dropped them) and shrink as root keys
    die; and — the regression the tracking exists for — after a page
    is reclaimed and its id recycled into a NEW prefix, no stale key
    chained through the old id can match."""
    c = PagedKVCache(n_pages=6, page_size=PS, kv_heads=1, head_dim=8)
    X, Y = _toks(10), _toks(20)
    c.acquire_prefix("a", X + Y)
    c.allocate("a", 2 * PS)
    c.register_prefix("a", X + Y)
    kX = (0, tuple(X))
    assert kX in c._children[0]  # root key tracked
    pX = c.tables["a"][0]
    assert kX in c._children.get(pX, set()) or \
        (pX, tuple(Y)) in c._children.get(pX, set())
    c.free("a")
    # full pressure recycles both pages; all keys (root included) die
    c.allocate("b", 5 * PS)
    assert kX not in c._children.get(0, set())  # no root-set leak
    assert c.match_prefix(X) == 0
    c.free("b")
    # recycle pX's id under NEW content W; the old (pX, Y) child key
    # must be gone — W followed by Y may only match W's page
    W = _toks(40)
    c.acquire_prefix("w", W)
    c.allocate("w", PS)
    c.register_prefix("w", W)
    assert c.acquire_prefix("v", W + Y) == PS
    assert c.lengths["v"] == PS
    c.free("v")
    c.free("w")
    _census_ok(c)


def test_acquire_rollback_restores_evictable_state():
    """The engine's admit contract at pool level: acquire revives
    parked pages; a failed allocate + free() returns them to the
    evictable pool (no refcount leak, chains still matchable)."""
    c = PagedKVCache(n_pages=6, page_size=PS, kv_heads=1, head_dim=8)
    X, Y = _toks(10), _toks(20)
    c.acquire_prefix("a", X + Y)
    c.allocate("a", 2 * PS)
    c.register_prefix("a", X + Y)
    c.free("a")
    assert c.acquire_prefix("b", X + Y) == 2 * PS  # revives both
    with pytest.raises(MemoryError):
        c.allocate("b", 20 * PS)
    c.rollback_acquire("b", X + Y)
    s = _census_ok(c)
    assert s["evictable_pages"] == 2 and not c._refs
    assert c.match_prefix(X + Y) == 2 * PS  # nothing lost
    # and the rolled-back acquire left NO trace in the hit stats
    assert s["hit_tokens"] == 0 and s["lookup_tokens"] == 2 * PS
    # and the retry admits cleanly
    assert c.acquire_prefix("b", X + Y) == 2 * PS
    c.allocate("b", 3 * PS)
    c.free("b")
    _census_ok(c)


# --- engine level -----------------------------------------------------------

@pytest.fixture(scope="module")
def srv_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    srv = llama_serving_decode_factory(model, max_len=64, page_size=8,
                                       n_pool_pages=33,
                                       batch_capacity=4,
                                       chunked_prefill=8)
    return srv, model, cfg


def _engine(srv, **kw):
    kw.setdefault("clock", "fixed")
    kw.setdefault("policy", "paged")
    return ServingEngine(serving=srv, slots=4, **kw)


def _trace(**kw):
    kw.setdefault("seed", 0)
    kw.setdefault("n_cohorts", 2)
    kw.setdefault("cohort_size", 4)
    kw.setdefault("rounds", 3)
    kw.setdefault("prefix_len", 24)
    kw.setdefault("tail_len", (2, 8))
    kw.setdefault("output_len", (4, 8))
    kw.setdefault("vocab_size", 97)
    kw.setdefault("round_gap", 80.0)
    return synthesize_recurring_prefix_trace(**kw)


def test_recurring_prefix_trace_shape():
    tr = _trace()
    assert tr == _trace()  # deterministic
    assert len(tr) == 24
    assert all(r.prefix_group is None for r in tr)  # no tag needed
    rounds = {}
    for r in tr:
        rnd = int(r.rid.split("-r")[1].split("c")[0])
        rounds.setdefault(rnd, []).append(r)
    assert sorted(rounds) == [1, 2, 3]
    # rounds temporally separated; cohort members share the prefix
    assert min(r.arrival for r in rounds[2]) \
        >= max(r.arrival for r in rounds[1]) + 70
    by_cohort = {}
    for r in tr:
        c = int(r.rid.split("c")[1].split(".")[0])
        by_cohort.setdefault(c, set()).add(tuple(r.prompt[:24]))
    assert all(len(v) == 1 for v in by_cohort.values())


def test_automatic_retention_serves_later_rounds(srv_model):
    """No prefix_group anywhere; round-1 requests all FINISH before
    round 2 arrives (liveness sharing would get zero hits) — yet every
    round >= 2 request hits the full retained prefix, outputs match
    the cache-off replay token-for-token, and the pool census holds."""
    srv, _, _ = srv_model
    tr = _trace()
    costs = {"prefill_unit": 1.0, "decode": 1.0}
    on = _engine(srv, fixed_costs=costs, prefix_cache=True).run(tr)
    off = _engine(srv, fixed_costs=costs, prefix_cache=False).run(tr)
    assert on.outputs == off.outputs  # greedy parity cached/uncached
    # liveness check: round 1 fully drained before round 2 arrived
    r2_start = min(r.arrival for r in tr if "-r2" in r.rid)
    assert all(on.metrics.request(r.rid)["finish"] < r2_start
               for r in tr if "-r1" in r.rid)
    # every later-round request hit its full 3-page prefix
    for r in tr:
        rnd = int(r.rid.split("-r")[1].split("c")[0])
        if rnd >= 2:
            assert on.prefix_cached[r.rid] >= 24, r.rid
    assert off.prefix_cached == {r.rid: 0 for r in tr}
    assert on.prefill_tokens < off.prefill_tokens * 0.7
    assert on.cache_stats["invariant_ok"] is True
    assert off.cache_stats["invariant_ok"] is True
    assert on.cache_stats["hit_tokens"] > 0
    assert on.pages_free_end == on.pages_total  # evictable counts as
    # reclaimable capacity, so retention is not a leak
    # report surfacing: hit fields only where hits happened
    rep_on, rep_off = on.report(), off.report()
    assert rep_on["prefix_cache_hit_tokens"] == \
        sum(on.prefix_cached.values())
    assert 0 < rep_on["prefix_cache_hit_rate"] <= 1
    assert rep_on["prefill_tokens_saved"] > 0
    assert not any("prefix" in k for k in rep_off)  # byte-compat


def test_determinism_with_caching_on_and_off(srv_model):
    """Same trace, same arm, twice -> identical outputs, slot log and
    report (the slot-log determinism the satellite asks for)."""
    srv, _, _ = srv_model
    tr = _trace(rounds=2)
    costs = {"prefill_unit": 1.0, "decode": 1.0}
    for on in (True, False):
        a = _engine(srv, fixed_costs=costs, prefix_cache=on).run(tr)
        b = _engine(srv, fixed_costs=costs, prefix_cache=on).run(tr)
        assert a.outputs == b.outputs
        assert a.slot_log == b.slot_log
        assert a.report() == b.report()
        assert a.cache_stats == b.cache_stats


def test_failed_allocate_rollback_is_leak_proof(srv_model):
    """A request whose allocate fails after automatic acquisition
    requeues WITHOUT leaking shared refcounts: the run completes, all
    requests finish, and the pool census balances at every turn."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    # pool sized so TWO requests cannot be resident together (5 usable
    # pages, 4-page footprints; sharing covers only 2): the second
    # admit ACQUIRES the shared prefix, fails allocate, and must
    # requeue with the refs rolled back until the first frees
    srv = llama_serving_decode_factory(model, max_len=48, page_size=8,
                                       n_pool_pages=6,
                                       batch_capacity=2,
                                       chunked_prefill=8)
    rng = np.random.default_rng(3)
    prefix = tuple(int(t) for t in rng.integers(1, 97, 16))
    tails = [tuple(int(t) for t in rng.integers(1, 97, 3))
             for _ in range(2)]
    tr = [Request(rid=f"q{i}", arrival=0.0, prompt=prefix + tails[i],
                  max_new_tokens=6) for i in range(2)]
    eng = ServingEngine(serving=srv, slots=2, policy="paged",
                        clock="fixed")
    res = eng.run(tr)
    assert set(res.outputs) == {"q0", "q1"}
    assert len(res.outputs["q0"]) == 6 and len(res.outputs["q1"]) == 6
    assert res.prefix_cached["q1"] == 16  # the requeue still HIT
    assert res.cache_stats["invariant_ok"] is True
    assert res.pages_free_end == res.pages_total
    # rolled-back acquires must not inflate the stats: q1's blocked
    # retries each undid their hit/lookup, so only the two SERVED
    # admits count (q0: 16 lookup 0 hit; q1: 16 lookup 16 hit)
    assert res.cache_stats["hit_tokens"] == 16
    assert res.cache_stats["lookup_tokens"] == 32
    # q1 admitted strictly after q0 released its slot (the blocked wave)
    rel0 = [t for t, ev, rid, _ in res.slot_log
            if rid == "q0" and ev == "release"][0]
    acq1 = [t for t, ev, rid, _ in res.slot_log
            if rid == "q1" and ev == "acquire"][0]
    assert acq1 >= rel0


def test_publish_exports_prefix_gauges(srv_model):
    from paddle_tpu.obs.metrics import MetricsRegistry
    srv, _, _ = srv_model
    res = _engine(srv, fixed_costs={"prefill_unit": 1.0, "decode": 1.0},
                  prefix_cache=True).run(_trace(rounds=2))
    reg = MetricsRegistry()
    rec = res.metrics.publish(registry=reg, prefix="pp")
    snap = reg.snapshot()
    assert snap["pp_prefix_cache_hit_tokens"] > 0
    assert "pp_prefill_tokens_saved" in snap
    assert "pp_prefix_cache_hit_rate" in snap
    assert rec["prefix_cache_hit_tokens"] > 0


def test_admit_trace_carries_cached_tokens(srv_model, tmp_path):
    """The obs satellite: admit instants carry the per-request hit
    count and trace_report folds it into the waterfall + summary."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import trace_report
    from paddle_tpu import obs
    srv, _, _ = srv_model
    tracer = obs.Tracer()
    res = _engine(srv, fixed_costs={"prefill_unit": 1.0, "decode": 1.0},
                  prefix_cache=True, trace=tracer).run(_trace(rounds=2))
    admits = [e for e in tracer.events
              if e.get("ph") == "i" and e.get("name") == "admit"]
    assert admits and all("cached" in e["args"] for e in admits)
    assert sum(e["args"]["cached"] for e in admits) \
        == sum(res.prefix_cached.values())
    path = str(tmp_path / "t.json")
    tracer.export(path)
    summary = trace_report.summarize(trace_report.load_trace(path))
    assert summary["prefix_hit_tokens"] == \
        sum(res.prefix_cached.values())
    text = trace_report.report(trace_report.load_trace(path))
    assert "hit=" in text


# --- scheduler level --------------------------------------------------------

def test_estimator_prefill_cost_per_chunk():
    flat = ServiceEstimator(prefill=2.0, decode=1.0)
    assert flat.prefill_cost(100) == 2.0  # no unit pricing: flat
    est = ServiceEstimator(prefill=2.0, decode=1.0, prefill_unit=0.5,
                           chunk_tokens=8)
    assert est.prefill_cost(None) == 2.0   # no probe: flat
    assert est.prefill_cost(24) == pytest.approx(1.5)
    assert est.prefill_cost(17) == pytest.approx(1.5)  # ceil to chunks
    assert est.prefill_cost(0) == pytest.approx(0.5)   # final chunk
    # EXACT pricing with the prompt length: what the engine's clock
    # charges is ceil(prompt/chunk) - cached//chunk (final chunk
    # always runs; a non-chunk-aligned cached prefix pays its partial
    # chunk — page 4 / chunk 8 / prompt 25 / cached 12 -> 3 chunks,
    # not ceil(13/8)=2)
    assert est.prefill_cost(13, prompt_tokens=25) == pytest.approx(1.5)
    assert est.prefill_cost(25, prompt_tokens=25) == pytest.approx(2.0)
    assert est.prefill_cost(0, prompt_tokens=24) == pytest.approx(0.5)
    with pytest.raises(ValueError, match="chunk_tokens"):
        ServiceEstimator(prefill_unit=1.0)
    with pytest.raises(ValueError, match="positive"):
        ServiceEstimator(prefill_unit=-1.0, chunk_tokens=8)


def test_qos_no_probe_prices_full_prompt():
    """Per-chunk clock pricing + prefix_cache OFF (match_prefix=None):
    feasibility must price the FULL prompt per chunk, not the flat
    per-call cost — a 4-chunk prompt with a 1-chunk deadline budget
    is shed, not admitted to miss."""
    from paddle_tpu.serving import QoSScheduler
    est = ServiceEstimator(prefill=1.0, decode=1.0, prefill_unit=1.0,
                           chunk_tokens=8)
    # 4 chunks x 1.0 prefill + decode 2 x 1.5 = 7.0 > deadline 5.0;
    # the flat cost (1.0) would have called it feasible (4.0 < 5.0)
    r = Request(rid="x", arrival=0.0, prompt=tuple(range(1, 33)),
                max_new_tokens=2, deadline_ms=5000.0)
    s = QoSScheduler(degrade_tiers=())
    s.enqueue(r, 0.0)
    dec = s.select(0.0, max_batch=1, est=est)
    assert not dec.wave and dec.shed


def test_qos_feasibility_is_cache_aware():
    """A deadline that only fits the CACHED prefill cost: flat pricing
    sheds the request, cache-aware pricing admits it at full budget."""
    from paddle_tpu.serving import QoSScheduler
    # flat estimate 4.0 = the honest uncached cost of this prompt (4
    # chunks x 1.0); per-chunk pricing can undercut it only by KNOWING
    # the cached length
    est = ServiceEstimator(prefill=4.0, decode=1.0, prefill_unit=1.0,
                           chunk_tokens=8)
    prompt = tuple(range(1, 33))  # 4 chunks uncached, 1 when cached
    # headroom 1.5, budget 2 -> decode 3.0; deadline 5.0: needs
    # prefill <= 2.0, i.e. <= 2 chunks
    r = Request(rid="x", arrival=0.0, prompt=prompt, max_new_tokens=2,
                deadline_ms=5000.0)
    s = QoSScheduler(degrade_tiers=())
    s.enqueue(r, 0.0)
    dec = s.select(0.0, max_batch=1, est=est)
    assert not dec.wave and dec.shed  # flat/uncached: infeasible
    s.reset()
    s.enqueue(r, 0.0)
    dec = s.select(0.0, max_batch=1, est=est,
                   match_prefix=lambda toks: 24)  # 3 pages cached
    assert [q.rid for q in dec.wave] == ["x"] and not dec.shed
    # and earlier wave members' prefills are priced by THEIR uncached
    # length: two cached requests fit where two uncached would not
    s.reset()
    r2 = Request(rid="y", arrival=0.1, prompt=prompt, max_new_tokens=2,
                 deadline_ms=6000.0)
    s.enqueue(r, 0.0)
    s.enqueue(r2, 0.1)
    dec = s.select(0.0, max_batch=2, est=est,
                   match_prefix=lambda toks: 24)
    assert [q.rid for q in dec.wave] == ["x", "y"]
    dec = s.select(0.0, max_batch=2, est=est)
    assert not dec.wave and len(dec.shed) == 2


def test_scheduled_engine_with_prefix_cache(srv_model):
    """The QoS loop composes with automatic caching: a recurring-
    prefix trace under the scheduler completes with hits, balanced
    census, and deterministic replay."""
    from paddle_tpu.serving import QoSScheduler
    srv, _, _ = srv_model
    tr = _trace(rounds=2)
    costs = {"prefill_unit": 1.0, "decode": 1.0}

    def run():
        return _engine(srv, fixed_costs=costs, prefix_cache=True,
                       scheduler=QoSScheduler()).run(tr)
    a, b = run(), run()
    assert a.report()["completed"] == len(tr)
    assert sum(a.prefix_cached.values()) > 0
    assert a.cache_stats["invariant_ok"] is True
    assert a.outputs == b.outputs and a.slot_log == b.slot_log


# --- the bench-gate contract ------------------------------------------------

def _run_gate(text, tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "BENCH_GATE_SERVING_BASELINE":
           str(tmp_path / "b.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_gate.py"),
         "serving", "-"], input=text, capture_output=True, text=True,
        timeout=60, cwd=repo, env=env)
    return r.returncode, json.loads(r.stdout.strip().splitlines()[-1])


def _px_row(cache, prefill_tokens, ttft2, n=32, res=0, ev=0, free=None,
            inv=True):
    free = n - res - ev if free is None else free
    return json.dumps({
        "bench": "serving_prefix", "cache": cache, "device": "cpu",
        "prefill_tokens": prefill_tokens, "ttft_round2_p50": ttft2,
        "cache_stats": {"n_pages": n, "resident_pages": res,
                        "evictable_pages": ev, "free_pages": free,
                        "invariant_ok": inv}})


def test_bench_gate_serving_prefix_rows(tmp_path):
    """serving_prefix family: savings + TTFT floors pass; sub-floor
    savings, broken census, diverging or UNVERIFIED outputs and
    missing arms all FAIL gracefully (a record, not a traceback)."""
    match = json.dumps({"bench": "serving_prefix_summary",
                        "outputs_match": True})
    ok = "\n".join([_px_row("off", 800, 18.0),
                    _px_row("on", 300, 6.0, ev=10), match])
    rc, rec = _run_gate(ok + "\n", tmp_path)
    assert rc == 0 and rec["gate"] == "pass"
    assert rec["prefill_tokens_saved_frac"] == pytest.approx(0.625)
    assert rec["ttft_round2_improvement"] == pytest.approx(3.0)

    # savings below floor
    rc, rec = _run_gate("\n".join([_px_row("off", 800, 18.0),
                                   _px_row("on", 700, 6.0),
                                   match]) + "\n", tmp_path)
    assert rc == 1 and "saved only" in rec["reason"]

    # TTFT improvement below floor
    rc, rec = _run_gate("\n".join([_px_row("off", 800, 6.5),
                                   _px_row("on", 300, 6.0),
                                   match]) + "\n", tmp_path)
    assert rc == 1 and "TTFT" in rec["reason"]

    # summary row missing entirely -> parity UNVERIFIED, never a pass
    rc, rec = _run_gate("\n".join([_px_row("off", 800, 18.0),
                                   _px_row("on", 300, 6.0)]) + "\n",
                        tmp_path)
    assert rc == 1 and "UNVERIFIED" in rec["reason"]

    # census broken (pages leaked)
    rc, rec = _run_gate("\n".join([_px_row("off", 800, 18.0),
                                   _px_row("on", 300, 6.0, ev=10,
                                           free=10), match]) + "\n",
                        tmp_path)
    assert rc == 1 and "accounting" in rec["reason"]

    # invariant flag tripped mid-run
    rc, rec = _run_gate("\n".join([_px_row("off", 800, 18.0, inv=False),
                                   _px_row("on", 300, 6.0),
                                   match]) + "\n", tmp_path)
    assert rc == 1 and "accounting" in rec["reason"]

    # diverging greedy outputs
    rows = "\n".join([_px_row("off", 800, 18.0),
                      _px_row("on", 300, 6.0),
                      json.dumps({"bench": "serving_prefix_summary",
                                  "outputs_match": False})])
    rc, rec = _run_gate(rows + "\n", tmp_path)
    assert rc == 1 and "DIVERGING" in rec["reason"]

    # missing arm -> graceful FAIL
    rc, rec = _run_gate(_px_row("on", 300, 6.0) + "\n", tmp_path)
    assert rc == 1 and "BOTH" in rec["reason"]

    # combined verdict when another family rides along
    rows = "\n".join([ok,
                      json.dumps({"bench": "serving_workload",
                                  "policy": "routed",
                                  "tokens_per_sec": 100.0}),
                      json.dumps({"bench": "serving_workload",
                                  "policy": "paged",
                                  "tokens_per_sec": 99.0})])
    rc, rec = _run_gate(rows + "\n", tmp_path)
    assert rc == 0 and rec.get("combined") is True
    assert rec["prefix_gate"] == "pass"
