"""ZeRO group-sharded parallelism: optimizer states (and, at stage 3,
params) must actually be sharded across the 'sharding' mesh axis — each
device's addressable shard is 1/N of the full array.

~ reference fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:48
(param→rank segmentation), group_sharded_stage3.py:58 (param sharding with
re-gather at use). Here GSPMD does the segmentation from NamedShardings.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


@pytest.fixture
def sharding_mesh():
    from paddle_tpu.distributed import topology as topo
    prev = topo.get_global_mesh()
    prev_hcg = topo.get_hybrid_communicate_group()
    topo.set_hybrid_communicate_group(None)  # isolate from other tests
    mesh = topo.build_mesh({"sharding": 8})
    topo.set_global_mesh(mesh)
    yield mesh
    topo.set_global_mesh(prev)
    topo.set_hybrid_communicate_group(prev_hcg)


def _train_one_step(model, opt):
    x = paddle.to_tensor(np.random.rand(4, 64).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(4, 64).astype(np.float32))
    loss = paddle.nn.functional.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


def _shard_fraction(arr):
    return arr.addressable_shards[0].data.size / arr.size


class TestGroupSharded:
    def test_stage_os_shards_moments_not_params(self, sharding_mesh):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        model = nn.Linear(64, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, "os")
        _train_one_step(model, opt)
        accs = [a for d in opt._accumulators.values() for a in d.values()
                if hasattr(a, "ndim") and a.ndim >= 1]
        assert accs, "no accumulators created"
        for a in accs:
            assert _shard_fraction(a) == pytest.approx(1 / 8), \
                f"moment not 1/8-sharded: {a.sharding}"
        # stage 1: params stay replicated (full copy on every device)
        for p in model.parameters():
            assert _shard_fraction(p._value) == pytest.approx(1.0)

    def test_stage_p_g_os_shards_params_too(self, sharding_mesh):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        model = nn.Linear(64, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, "p_g_os")
        l0 = _train_one_step(model, opt)
        w = model.weight._value
        assert _shard_fraction(w) == pytest.approx(1 / 8), \
            f"stage-3 param not sharded: {w.sharding}"
        # training still works on sharded params (all-gather at use)
        l1 = _train_one_step(model, opt)
        assert np.isfinite(l1) and l1 < l0 * 2

    def test_sharded_matches_unsharded_update(self, sharding_mesh):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        paddle.seed(7)
        ref = nn.Linear(64, 64)
        paddle.seed(7)
        shd = nn.Linear(64, 64)
        opt_ref = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=ref.parameters())
        opt_shd = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=shd.parameters())
        shd, opt_shd = group_sharded_parallel(shd, opt_shd, "os_g")
        np.random.seed(3)
        for _ in range(3):
            x = paddle.to_tensor(np.random.rand(4, 64).astype(np.float32))
            y = paddle.to_tensor(np.random.rand(4, 64).astype(np.float32))
            for m, o in ((ref, opt_ref), (shd, opt_shd)):
                loss = paddle.nn.functional.mse_loss(m(x), y)
                loss.backward()
                o.step()
                o.clear_grad()
        np.testing.assert_allclose(np.asarray(ref.weight._value),
                                   np.asarray(shd.weight._value),
                                   rtol=1e-5, atol=1e-6)


class TestZeroOffload:
    def test_offload_places_moments_in_host_memory(self, sharding_mesh):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.optimizer.optimizer import _host_memory_supported
        if not _host_memory_supported():
            pytest.skip("backend has no pinned_host memory")
        model = nn.Linear(64, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, "os", offload=True)
        l0 = _train_one_step(model, opt)
        accs = [a for d in opt._accumulators.values() for a in d.values()
                if hasattr(a, "ndim") and a.ndim >= 1]
        assert accs
        for a in accs:
            assert a.sharding.memory_kind == "pinned_host", a.sharding
            assert _shard_fraction(a) == pytest.approx(1 / 8)
        # params stay in device memory; training still converges
        for p in model.parameters():
            assert p._value.sharding.memory_kind != "pinned_host"
        l1 = _train_one_step(model, opt)
        assert np.isfinite(l1)

    def test_offload_update_matches_device_states(self, sharding_mesh):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.optimizer.optimizer import _host_memory_supported
        if not _host_memory_supported():
            pytest.skip("backend has no pinned_host memory")
        losses = {}
        for offload in (False, True):
            paddle.seed(11)
            np.random.seed(11)
            model = nn.Linear(64, 64)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=model.parameters())
            model, opt = group_sharded_parallel(model, opt, "os",
                                                offload=offload)
            losses[offload] = [_train_one_step(model, opt)
                               for _ in range(3)]
        np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)

    def test_state_dict_snapshot_survives_step(self, sharding_mesh):
        # regression: accumulator donation must not invalidate state_dict
        # snapshots taken before a later step (checkpoint-then-continue)
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        model = nn.Linear(64, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, "os")
        _train_one_step(model, opt)
        snap = opt.state_dict()
        _train_one_step(model, opt)
        for v in snap.values():
            if hasattr(v, "numpy"):
                assert np.isfinite(v.numpy()).all()
            elif hasattr(v, "items"):
                for x in v.values():
                    arr = getattr(x, "_value", x)
                    if hasattr(arr, "shape"):
                        assert np.isfinite(np.asarray(arr)).all()

    def test_decorate_o2_after_step_recreates_jit(self, sharding_mesh):
        # regression: amp.decorate(level='O2') retrofits '_master' into
        # existing accumulators; the cached mesh-path jit bakes
        # out_shardings over the OLD accumulator pytree and must be
        # recreated (keyed on accumulator structure), not reused.
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        model = nn.Linear(64, 64)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt = group_sharded_parallel(model, opt, "os")
        _train_one_step(model, opt)  # compiles the {m, v} update
        paddle.amp.decorate(model, opt, level="O2")
        loss = _train_one_step(model, opt)  # must retrace, not crash
        assert np.isfinite(loss)
        assert all("_master" in a for a in opt._accumulators.values())


def test_factory_offload_moments_matches_device_states():
    # compiled-factory offload (~ group_sharded_stage3.py:58): moments in
    # pinned host memory must produce the identical training trajectory
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama import llama_train_step_factory

    cfg = LlamaConfig.tiny(vocab=128, hidden=32, layers=1, heads=2)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)

    losses = {}
    for offload in (False, True):
        paddle.seed(7)
        model = LlamaForCausalLM(cfg)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        params, opt, step, _ = llama_train_step_factory(
            model, mesh, learning_rate=1e-2, remat=False,
            offload_moments=offload)
        if offload:
            # some CPU jax builds expose no pinned_host memory space at
            # all — there offload degrades to a no-op placement
            # (train_utils.with_memory_kind) and the trajectory-parity
            # assertion below is the whole test
            from paddle_tpu.optimizer.optimizer import (
                _host_memory_supported)
            if _host_memory_supported():
                assert all(a.sharding.memory_kind == "pinned_host"
                           for a in opt["m"].values())
        ls = []
        for _ in range(3):
            params, opt, loss = step(params, opt, tokens, labels)
            ls.append(float(loss))
        losses[offload] = ls
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
