"""Multi-process compiled-mesh execution: ONE jitted hybrid-parallel train
step spanning processes.

~ reference test_dist_base.py:1327 (spawned-rank dist tests): 2 processes x
4 local CPU devices rendezvous via ``init_parallel_env`` (the launch CLI
provides the PADDLE_MASTER/rank env contract) into ONE global 8-device mesh
{'data':2,'sep':2,'model':2}, then run the REAL ``llama_train_step_factory``
program — the untested seam between the single-process virtual-mesh dryrun
and a real pod is exactly this cross-process GSPMD execution (the factory's
device_put of host params onto a partly non-addressable mesh, collectives
crossing the process boundary).

Losses must be identical on every rank (replicated output) and match the
single-process 8-virtual-device oracle step for step.
"""
import pytest

pytestmark = pytest.mark.slow  # multi-process/e2e: full-suite lane only
import json
import os
import subprocess
import sys
import textwrap

import numpy as np

TRAINER = textwrap.dedent("""
    import json
    import os
    import sys
    sys.path.insert(0, "/root/repo")
    import jax
    jax.config.update("jax_platforms", "cpu")

    rank = int(os.environ.get("PADDLE_GLOBAL_RANK", "0"))
    world = int(os.environ.get("PADDLE_WORLD_SIZE", "1"))
    if world > 1:
        # the launch master's TCPStore owns PADDLE_MASTER's port; the jax
        # coordinator needs its own
        host, port = os.environ["PADDLE_MASTER"].split(":")
        os.environ["PADDLE_MASTER"] = f"{host}:{int(port) + 53}"

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    assert jax.process_count() == world or world == 1
    devs = np.asarray(jax.devices()[:8])
    mesh = Mesh(devs.reshape(2, 2, 2), ("data", "sep", "model"))

    paddle.seed(0)
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama import llama_train_step_factory
    cfg = LlamaConfig.tiny(vocab=256, hidden=64, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    params, opt_state, step, _ = llama_train_step_factory(
        model, mesh, learning_rate=1e-3, remat=True)

    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        losses.append(float(np.asarray(jax.device_get(loss))))

    out = os.environ["TEST_OUT_DIR"]
    with open(os.path.join(out, f"loss_rank{rank}.json"), "w") as f:
        json.dump(losses, f)
""")


def _trainer_env(out_dir, n_local_devices):
    env = dict(os.environ)
    env["TEST_OUT_DIR"] = str(out_dir)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_GLOBAL_RANK", None)
    env.pop("PADDLE_WORLD_SIZE", None)
    env["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={n_local_devices}"
    return env


def _run(tmp_path, nproc):
    script = tmp_path / "mesh_trainer.py"
    script.write_text(TRAINER)
    out = tmp_path / f"np{nproc}"
    out.mkdir()
    # every process contributes 8//nproc local devices to the global mesh
    env = _trainer_env(out, 8 // nproc)
    if nproc == 1:
        proc = subprocess.run([sys.executable, str(script)],
                              cwd="/root/repo", env=env,
                              capture_output=True, text=True, timeout=600)
    else:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", str(nproc), str(script)],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=600)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    losses = []
    for r in range(nproc):
        p = out / f"loss_rank{r}.json"
        assert p.exists(), \
            f"rank {r} wrote no losses: {proc.stdout}\n{proc.stderr}"
        losses.append(json.loads(p.read_text()))
    return np.asarray(losses)


@pytest.mark.dist_retry(n=1)
def test_two_process_global_mesh_train_step(tmp_path):
    single = _run(tmp_path, 1)[0]
    two = _run(tmp_path, 2)
    np.testing.assert_allclose(two[0], two[1], rtol=1e-6)
    np.testing.assert_allclose(two[0], single, rtol=1e-4, atol=1e-6)
    assert single[-1] < single[0], "loss did not decrease"


@pytest.mark.dist_retry(n=1)
def test_two_node_launch_httpmaster_rendezvous(tmp_path):
    """The --nnodes > 1 path: two launch pods (node_rank 0/1) rendezvous
    through HTTPMaster.sync_peers, each contributing one trainer to ONE
    jax.distributed global mesh (~ the reference's multi-node launch
    contract, launch/controllers/collective.py + controllers/master.py).
    """
    import subprocess
    import time as _time
    script = tmp_path / "mesh_trainer.py"
    src = TRAINER.replace("jax.devices()[:8]", "jax.devices()[:2]") \
                 .replace("devs.reshape(2, 2, 2)", "devs.reshape(1, 1, 2)")
    assert "reshape(1, 1, 2)" in src
    script.write_text(src)
    out = tmp_path / "nodes"
    out.mkdir()
    env = _trainer_env(out, 1)
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    master = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    pods = []
    try:
        for nr in (0, 1):
            pods.append(subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--master", master, "--nnodes", "2",
                 "--node_rank", str(nr),
                 "--nproc_per_node", "1", str(script)],
                cwd="/root/repo", env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
            _time.sleep(0.5)  # node 0 binds the HTTP master first
        outs = [p.communicate(timeout=600) for p in pods]
    finally:
        for p in pods:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(pods, outs):
        assert p.returncode == 0, so + "\n" + se
    losses = []
    for r in range(2):
        f = out / f"loss_rank{r}.json"
        assert f.exists(), (outs[0][0], outs[0][1], outs[1][1])
        losses.append(json.loads(f.read_text()))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    assert losses[0][-1] < losses[0][0]
