"""Numeric-gradient sweep across the differentiable op surface.

Extends the OpTest pillar (~ reference op_test.py check_grad:1817 +
white_list-driven coverage): every entry runs central finite differences
vs the tape's analytic gradient on a small smooth-domain input. Input
generators keep values away from non-smooth points (|x| floor for
abs-like kinks, open intervals for inverse-trig domains).
"""
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import check_grad

rng = np.random.default_rng(7)


def _reseed(name: str):
    """Deterministic per-op inputs regardless of test selection/order
    (crc32: stable across processes, unlike str hash)."""
    global rng
    rng = np.random.default_rng(zlib.crc32(name.encode()))


def _std(shape=(2, 3)):
    return rng.normal(0, 1, shape).astype(np.float32)


def _pos(shape=(2, 3), lo=0.2, hi=2.0):
    return rng.uniform(lo, hi, shape).astype(np.float32)


def _open01(shape=(2, 3)):
    return rng.uniform(0.05, 0.95, shape).astype(np.float32)


def _sym(shape=(2, 3), r=0.9):
    return rng.uniform(-r, r, shape).astype(np.float32)


def _away_from_zero(shape=(2, 3)):
    x = rng.uniform(0.3, 1.5, shape).astype(np.float32)
    return x * np.where(rng.random(shape) < 0.5, -1, 1).astype(np.float32)


UNARY = [
    ("tanh", paddle.tanh, _std, {}),
    ("sigmoid", F.sigmoid, _std, {}),
    ("exp", paddle.exp, _std, {}),
    ("expm1", paddle.expm1, _std, {}),
    ("log", paddle.log, _pos, {}),
    ("log1p", paddle.log1p, _pos, {}),
    ("log2", paddle.log2, _pos, {}),
    ("log10", paddle.log10, _pos, {}),
    ("sqrt", paddle.sqrt, _pos, {}),
    ("rsqrt", paddle.rsqrt, _pos, {}),
    ("sin", paddle.sin, _std, {}),
    ("cos", paddle.cos, _std, {}),
    ("tan", paddle.tan, lambda: _sym(r=0.7), {}),
    ("asin", paddle.asin, _sym, {}),
    ("acos", paddle.acos, _sym, {}),
    ("atan", paddle.atan, _std, {}),
    ("sinh", paddle.sinh, _std, {}),
    ("cosh", paddle.cosh, _std, {}),
    ("asinh", paddle.asinh, _std, {}),
    ("acosh", paddle.acosh, lambda: _pos(lo=1.2, hi=3.0), {}),
    ("atanh", paddle.atanh, _sym, {}),
    ("erf", paddle.erf, _std, {}),
    ("reciprocal", paddle.reciprocal, _away_from_zero, {}),
    ("square", paddle.square, _std, {}),
    ("logit", paddle.logit, _open01, {}),
    ("silu", F.silu, _std, {}),
    ("softplus", F.softplus, _std, {}),
    ("softsign", F.softsign, _away_from_zero, {}),
    ("mish", F.mish, _std, {}),
    ("gelu", F.gelu, _std, {}),
    ("elu", F.elu, _away_from_zero, {}),
    ("selu", F.selu, _away_from_zero, {}),
    ("celu", F.celu, _away_from_zero, {}),
    # hardswish kinks at x = +-3; (-2, 2) is its smooth quadratic region
    ("hardswish", F.hardswish, lambda: _sym(r=2.0), {}),
    ("tanhshrink", F.tanhshrink, _std, {}),
    ("softshrink", F.softshrink, lambda: _away_from_zero() * 2, {}),
    ("hardshrink", F.hardshrink, lambda: _away_from_zero() * 2, {}),
    ("log_sigmoid", F.log_sigmoid, _std, {}),
    ("swish", F.swish, _std, {}),
    ("logsumexp", paddle.logsumexp, _std, {}),
    ("prod", paddle.prod, _away_from_zero, {}),
    ("cumsum", paddle.cumsum, _std, {}),
    ("cumprod", paddle.cumprod, _away_from_zero, {"dim": 1}),
    ("trace", paddle.trace, lambda: _std((3, 3)), {}),
    ("frac", paddle.frac, lambda: _pos(lo=0.1, hi=0.9) + 2.0, {}),
    ("rad2deg", paddle.rad2deg, _std, {}),
    ("deg2rad", paddle.deg2rad, _std, {}),
    ("roll", paddle.roll, _std, {"shifts": 1}),
    ("flip", paddle.flip, _std, {"axis": 0}),
]

BINARY = [
    ("maximum", paddle.maximum,
     lambda: (_std(), _std() + 3.0), {}),          # no ties
    ("minimum", paddle.minimum,
     lambda: (_std(), _std() + 3.0), {}),
    ("fmax", paddle.fmax, lambda: (_std(), _std() + 3.0), {}),
    ("fmin", paddle.fmin, lambda: (_std(), _std() + 3.0), {}),
    ("atan2", paddle.atan2, lambda: (_pos(), _pos()), {}),
    ("logaddexp", paddle.logaddexp, lambda: (_std(), _std()), {}),
    ("kron", paddle.kron, lambda: (_std((2, 2)), _std((2, 2))), {}),
    ("cross", paddle.cross, lambda: (_std((3, 3)), _std((3, 3))), {}),
    ("dist", paddle.dist, lambda: (_std(), _std() + 2.0), {}),
    ("lerp", paddle.lerp,
     lambda: (_std(), _std(), _open01()), {}),
]


@pytest.mark.parametrize("name,api,gen,attrs",
                         UNARY, ids=[u[0] for u in UNARY])
def test_unary_grad(name, api, gen, attrs):
    _reseed(name)
    check_grad(api, [gen()], attrs=attrs)


@pytest.mark.parametrize("name,api,gen,attrs",
                         BINARY, ids=[b[0] for b in BINARY])
def test_nary_grad(name, api, gen, attrs):
    _reseed(name)
    check_grad(api, list(gen()), attrs=attrs)
