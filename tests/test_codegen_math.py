"""YAML-codegen math families: generated ops vs numpy oracles + grads.

~ the reference's api.yaml-driven generation (api_gen.py) validated by
OpTest (unittests/op_test.py check_output/check_grad): each generated op
must match its numpy oracle and carry a derived VJP, static capture and
eval_shape infermeta.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.ops import OP_REGISTRY, infer_meta
from paddle_tpu.ops.codegen import load_specs

UN_ORACLES = {
    "exp": np.exp, "log1p": np.log1p, "sqrt": np.sqrt,
    "sinh": np.sinh, "atan": np.arctan, "erf": None,
    "rsqrt": lambda x: 1.0 / np.sqrt(x), "frac": lambda x: x - np.trunc(x),
    "deg2rad": np.deg2rad,
}
BIN_ORACLES = {
    "add": np.add, "divide": np.divide, "atan2": np.arctan2,
    "copysign": np.copysign, "logaddexp": np.logaddexp,
    "heaviside": np.heaviside,
}


class TestGeneratedMathFamilies:
    def test_spec_breadth_and_groups(self):
        specs = load_specs()
        by_group = {}
        for s in specs:
            by_group.setdefault(s.get("group", "misc"), []).append(s["op"])
        assert len(by_group.get("math", [])) >= 55
        # every math-group op is registered and callable
        for name in by_group["math"]:
            assert name in OP_REGISTRY, name

    @pytest.mark.parametrize("name", sorted(UN_ORACLES))
    def test_unary_oracle(self, name):
        oracle = UN_ORACLES[name]
        if oracle is None:
            pytest.skip("no simple numpy oracle")
        x = np.abs(np.random.default_rng(0).normal(
            1.0, 0.3, (3, 4))).astype(np.float32)
        got = OP_REGISTRY[name](paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, oracle(x), rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("name", sorted(BIN_ORACLES))
    def test_binary_oracle(self, name):
        oracle = BIN_ORACLES[name]
        rng = np.random.default_rng(1)
        x = np.abs(rng.normal(1.0, 0.3, (3, 4))).astype(np.float32)
        y = np.abs(rng.normal(1.0, 0.3, (3, 4))).astype(np.float32)
        got = OP_REGISTRY[name](paddle.to_tensor(x),
                                paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(got, oracle(x, y), rtol=2e-6, atol=2e-6)

    def test_generated_grad_numeric(self):
        # d/dx log1p(x) = 1/(1+x) — numeric check like OpTest.check_grad
        x = paddle.to_tensor(np.array([0.5, 1.5], np.float32))
        x.stop_gradient = False
        paddle.sum(OP_REGISTRY["log1p"](x)).backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   1.0 / (1.0 + x.numpy()), rtol=1e-6)

    def test_infermeta_on_generated_family(self):
        meta = infer_meta("hypot",
                          jax.ShapeDtypeStruct((2, 1), np.float32),
                          jax.ShapeDtypeStruct((1, 5), np.float32))
        assert tuple(meta.shape) == (2, 5)

    def test_static_capture_of_generated_op(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            x = static.data("x", [2], "float32")
            prog = static.default_main_program()
            before = prog._n_ops
            y = OP_REGISTRY["exp"](x)
            assert prog._n_ops == before + 1  # captured, not executed
            exe = static.Executor()
            out, = exe.run(prog, feed={"x": np.zeros(2, np.float32)},
                           fetch_list=[y])
            np.testing.assert_allclose(out, np.ones(2))
        finally:
            paddle.disable_static()

    def test_int_ops_nondiff_by_dtype(self):
        a = paddle.to_tensor(np.array([12, 18], np.int32))
        b = paddle.to_tensor(np.array([8, 27], np.int32))
        np.testing.assert_array_equal(OP_REGISTRY["gcd"](a, b).numpy(),
                                      [4, 9])
