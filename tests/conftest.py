"""Test config: force CPU backend with 8 virtual devices.

Mirrors the reference test strategy (SURVEY.md §4): numpy-oracle op tests on
CPU; distributed parity over a virtual device mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 is the gloo analog).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# JAX_PLATFORMS; the programmatic config update still wins if applied before
# first backend use.
if os.environ.get("PADDLE_TPU_TEST_ON_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


def _free_port() -> int:
    """A port currently free on localhost (bind-to-0 probe). Avoids
    collisions between concurrently running suites/processes that the old
    hard-coded ports suffered."""
    import socket
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def free_port():
    return _free_port()


@pytest.fixture
def free_port_factory():
    return _free_port


# --- dist-test retry + quarantine discipline ------------------------------
# ~ reference dist_test.sh (retry loop around multi-process tests) and
# tools/get_quick_disable_lt.py (quarantine list fetched before the run).
# Multi-process rendezvous tests are load-sensitive by nature; marked
# tests get bounded reruns, and tests/quarantine.txt names node-id
# substrings to skip outright (one per line, '#' comments).

def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "dist_retry(n=1): rerun a load-sensitive multi-process test up to "
        "n extra times on failure (~ dist_test.sh retry discipline)")


def pytest_collection_modifyitems(config, items):
    import os

    # 1) quarantine: node-id substrings in quarantine.txt skip outright
    qpath = os.path.join(os.path.dirname(__file__), "quarantine.txt")
    patterns = []
    if os.path.exists(qpath):
        with open(qpath) as f:
            # node-id substring, optional trailing '# issue-ref' comment
            patterns = [ln.split("#")[0].strip() for ln in f
                        if ln.split("#")[0].strip()]
    if patterns:
        skip = pytest.mark.skip(
            reason="quarantined (tests/quarantine.txt)")
        for item in items:
            if any(p in item.nodeid for p in patterns):
                item.add_marker(skip)

    # 2) duration-based slow marking (round-4 verdict item 10): node
    # ids measured >= 8s in the full-suite --durations run live in
    # tests/slow_tests.txt; they get the `slow` marker in addition to
    # the file-level pytestmark on the multi-process/e2e modules, so
    # `-m "not slow"` is a genuinely fast lane on this 1-core host
    lpath = os.path.join(os.path.dirname(__file__), "slow_tests.txt")
    if os.path.exists(lpath):
        with open(lpath) as f:
            slow_ids = {ln.strip() for ln in f
                        if ln.strip() and not ln.startswith("#")}
        for item in items:
            if item.nodeid in slow_ids:
                item.add_marker(pytest.mark.slow)


def pytest_runtest_protocol(item, nextitem):
    m = item.get_closest_marker("dist_retry")
    if m is None:
        return None
    retries = int(m.kwargs.get("n", m.args[0] if m.args else 1))
    from _pytest.runner import runtestprotocol
    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    for attempt in range(retries + 1):
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
        if not any(r.failed for r in reports) or attempt == retries:
            for r in reports:
                item.ihook.pytest_runtest_logreport(report=r)
            break
        import warnings
        warnings.warn(f"dist_retry: {item.nodeid} failed attempt "
                      f"{attempt + 1}/{retries + 1}, retrying")
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True

