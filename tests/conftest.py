"""Test config: force CPU backend with 8 virtual devices.

Mirrors the reference test strategy (SURVEY.md §4): numpy-oracle op tests on
CPU; distributed parity over a virtual device mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 is the gloo analog).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# JAX_PLATFORMS; the programmatic config update still wins if applied before
# first backend use.
if os.environ.get("PADDLE_TPU_TEST_ON_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


def _free_port() -> int:
    """A port currently free on localhost (bind-to-0 probe). Avoids
    collisions between concurrently running suites/processes that the old
    hard-coded ports suffered."""
    import socket
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def free_port():
    return _free_port()


@pytest.fixture
def free_port_factory():
    return _free_port
