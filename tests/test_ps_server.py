"""Parameter-server RPC: dense/sparse tables over the PSServer data plane.

~ reference PS tests (test_dist_fleet_ps*.py spawn brpc servers+trainers
on localhost): here the threaded PSServer plays brpc, clients exercise
pull/push/save/load and the geo-style async push path, plus an
end-to-end embedding regression showing the PS actually learns.
"""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (AdagradRule, DenseTable, PSClient,
                                       PSServer, SparseTable)


@pytest.fixture
def server():
    srv = PSServer(port=0)
    yield srv
    srv.stop()


def _client(srv, table_id=0):
    return PSClient(server_addr=f"127.0.0.1:{srv.port}", table_id=table_id)


class TestRpc:
    def test_sparse_roundtrip(self, server):
        server.add_sparse_table(0, dim=4, lr=0.5, seed=1)
        c = _client(server)
        rows = c.pull_sparse(np.array([5, 9]))
        assert rows.shape == (2, 4)
        c.push_sparse(np.array([5]), np.ones((1, 4), np.float32))
        after = c.pull_sparse(np.array([5]))
        np.testing.assert_allclose(after[0], rows[0] - 0.5, rtol=1e-6)
        assert c.table_size() == 2
        c.close()

    def test_dense_roundtrip(self, server):
        server.add_dense_table(1, size=6, lr=0.1,
                               init=np.arange(6, dtype=np.float32))
        c = _client(server, table_id=1)
        np.testing.assert_allclose(c.pull_dense(), np.arange(6))
        c.push_dense(np.ones(6, np.float32))
        np.testing.assert_allclose(c.pull_dense(), np.arange(6) - 0.1,
                                   rtol=1e-6)
        c.set_dense(np.zeros(6))
        np.testing.assert_allclose(c.pull_dense(), 0.0)
        c.close()

    def test_error_propagates(self, server):
        c = _client(server, table_id=42)  # no such table
        with pytest.raises(RuntimeError, match="no table"):
            c.pull_dense()
        c.close()

    def test_save_load_via_rpc(self, server, tmp_path):
        server.add_sparse_table(0, dim=3, seed=2)
        c = _client(server)
        c.pull_sparse(np.array([1, 2]))
        path = str(tmp_path / "t.pkl")
        c.save(path)
        srv2 = PSServer(port=0)
        try:
            srv2.add_sparse_table(0, dim=3)
            c2 = _client(srv2)
            c2.load(path)
            assert c2.table_size() == 2
            np.testing.assert_allclose(c2.pull_sparse(np.array([1])),
                                       c.pull_sparse(np.array([1])))
            c2.close()
        finally:
            srv2.stop()
        c.close()

    def test_concurrent_clients(self, server):
        server.add_dense_table(0, size=1, lr=1.0)
        n, per = 8, 25

        def worker():
            c = _client(server)
            for _ in range(per):
                c.push_dense(np.array([-1.0], np.float32))
            c.close()

        ts = [threading.Thread(target=worker) for _ in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        c = _client(server)
        # every push applied exactly once despite 8 concurrent connections
        np.testing.assert_allclose(c.pull_dense(), [float(n * per)])
        c.close()


class TestRules:
    def test_adagrad_decreases_effective_lr(self):
        t = SparseTable(dim=2, lr=1.0, rule="adagrad", seed=0)
        t.pull(np.array([0]))
        before = t.pull(np.array([0]))[0].copy()
        g = np.ones((1, 2), np.float32)
        t.push(np.array([0]), g)
        step1 = before - t.pull(np.array([0]))[0]
        prev = t.pull(np.array([0]))[0].copy()
        t.push(np.array([0]), g)
        step2 = prev - t.pull(np.array([0]))[0]
        assert (step2 < step1).all()  # accumulated G^2 shrinks the step

    def test_rule_objects(self):
        r = AdagradRule(lr=0.5)
        row = np.array([1.0], np.float32)
        st = r.init_state(1)
        st = r.update(row, np.array([2.0], np.float32), st)
        assert row[0] < 1.0 and st[0] == 4.0


class TestAsyncPush:
    def test_geo_style_async_flush(self, server):
        server.add_sparse_table(0, dim=2, lr=0.1)
        c = _client(server)
        c.pull_sparse(np.array([7]))
        base = c.pull_sparse(np.array([7]))[0].copy()
        for _ in range(10):
            c.async_push_sparse(np.array([7]), np.ones((1, 2), np.float32))
        c.flush()
        after = c.pull_sparse(np.array([7]))[0]
        np.testing.assert_allclose(after, base - 1.0, rtol=1e-5)
        c.close()


class TestEndToEnd:
    def test_embedding_regression_learns(self, server):
        """PS-style training loop: sparse embeddings on the server, dense
        head trained locally — the canonical PS workload shape."""
        rng = np.random.default_rng(0)
        dim, n_ids = 8, 20
        server.add_sparse_table(0, dim=dim, lr=0.3, seed=3)
        c = _client(server)
        true_emb = rng.normal(0, 1, (n_ids, dim)).astype(np.float32)
        w = np.ones(dim, np.float32)  # fixed linear head
        losses = []
        for it in range(60):
            ids = rng.integers(0, n_ids, 16)
            y = true_emb[ids] @ w
            rows = c.pull_sparse(ids)
            pred = rows @ w
            err = pred - y                       # (16,)
            losses.append(float(np.mean(err ** 2)))
            grad_rows = 2 * err[:, None] * w[None, :] / len(ids)
            c.push_sparse(ids, grad_rows.astype(np.float32))
        assert losses[-1] < 0.2 * losses[0], (losses[0], losses[-1])
        c.close()
