"""Block-sparse (splash) flash attention vs a dense masked oracle.

~ sparse_attention_op.cu's role, but with masked blocks SKIPPED: the
kernel walks scalar-prefetched per-block index lists, so compute scales
with pattern density. CPU runs use pallas interpret mode.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.splash_attention import splash_attention


def _dense_oracle(q, k, v, block_mask, bq, bk, causal):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(D)
    mask = np.kron(np.asarray(block_mask, bool),
                   np.ones((bq, bk), bool))
    if causal:
        mask = mask & np.tril(np.ones((Sq, Sk), bool))
    scores = jnp.where(jnp.asarray(mask), scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no live key: all -1e30 -> softmax uniform; zero them like
    # the kernel does
    any_live = jnp.asarray(mask.any(-1))[None, None, :, None]
    return jnp.where(any_live,
                     jnp.einsum("bhqk,bhkd->bhqd", probs,
                                v.astype(jnp.float32)), 0.0)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_oracle(causal):
    rng = np.random.default_rng(0)
    B, H, S, D, bq, bk = 1, 2, 512, 64, 128, 128
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    nq, nk = S // bq, S // bk
    # local + strided pattern (BigBird-ish), ~50% dense
    bm = np.zeros((nq, nk), bool)
    for i in range(nq):
        bm[i, max(0, i - 1):i + 1] = True
        bm[i, 0] = True
    out = splash_attention(q, k, v, bm, causal)
    ref = _dense_oracle(q, k, v, bm, bq, bk, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_empty_rows_output_zero():
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 1, 256, 64)), jnp.float32)
    bm = np.zeros((2, 2), bool)
    bm[0, 0] = True  # second q block attends to NOTHING
    out = np.asarray(splash_attention(q, q, q, bm))
    assert np.abs(out[0, 0, 128:]).max() == 0.0
    assert np.abs(out[0, 0, :128]).max() > 0.0


def test_gradients_match_dense_oracle():
    rng = np.random.default_rng(2)
    B, H, S, D, bq, bk = 1, 1, 256, 64, 128, 128
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    bm = np.array([[True, False], [True, True]])

    def f_splash(q, k, v):
        return jnp.sum(splash_attention(q, k, v, bm, True) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(_dense_oracle(q, k, v, bm, bq, bk, True) ** 2)

    gs = jax.grad(f_splash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gs, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_jit_and_pattern_validation():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 1, 256, 64)), jnp.float32)
    bm = np.ones((2, 2), bool)
    jitted = jax.jit(lambda a: splash_attention(a, a, a, bm, True))
    out = jitted(q)
    assert np.isfinite(np.asarray(out)).all()
    with pytest.raises(ValueError, match="does not tile"):
        splash_attention(q, q, q, np.ones((3, 2), bool))


def test_above_diagonal_live_block_rows_zero_under_causal():
    # regression: a live block entirely ABOVE the causal diagonal left
    # p = exp2(0) = 1 mass (finite NEG_INF), outputting mean(V) for rows
    # with no visible key; backward overflowed exp2(s - (-inf))
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((1, 1, 256, 64)), jnp.float32)
    bm = np.array([[False, True],   # q block 0 sees ONLY future keys
                   [True, True]])
    out = splash_attention(q, q, q, bm, True)
    ref = _dense_oracle(q, q, q, bm, 128, 128, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    g = jax.grad(lambda a: jnp.sum(splash_attention(a, a, a, bm, True)
                                   ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_functional_wrapper_paddle_layout():
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import block_sparse_attention
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2, 256, 2, 64)).astype(np.float32)
    bm = np.tril(np.ones((2, 2), bool))
    out = block_sparse_attention(paddle.to_tensor(x), paddle.to_tensor(x),
                                 paddle.to_tensor(x), bm, is_causal=True)
    assert out.shape == [2, 256, 2, 64]
    qt = jnp.swapaxes(jnp.asarray(x), 1, 2)
    ref = _dense_oracle(qt, qt, qt, bm, 128, 128, True)
    np.testing.assert_allclose(out.numpy(),
                               np.swapaxes(np.asarray(ref), 1, 2),
                               rtol=2e-4, atol=2e-4)


class TestLlamaSlidingWindow:
    """config.sliding_window routes attention through the banded splash
    kernel (flash-eligible shapes) or a window-masked dense path; both
    must match a full-model oracle built with an explicit window mask."""

    def _logits(self, cfg, tokens):
        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaForCausalLM
        paddle.seed(11)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m(paddle.to_tensor(tokens)).numpy()

    def test_small_shape_dense_window_matches_full_when_window_covers(self):
        from paddle_tpu.models.nlp import LlamaConfig
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 128, (2, 16)).astype(np.int32)
        cfg = LlamaConfig.tiny(vocab=128, hidden=32, layers=1, heads=2,
                               kv_heads=2)
        full = self._logits(cfg, tokens)
        cfg_w = LlamaConfig.tiny(vocab=128, hidden=32, layers=1, heads=2,
                                 kv_heads=2)
        cfg_w.sliding_window = 16  # covers the whole sequence
        same = self._logits(cfg_w, tokens)
        np.testing.assert_allclose(same, full, rtol=1e-5, atol=1e-5)
        cfg_w.sliding_window = 4   # actually windowed: must differ
        windowed = self._logits(cfg_w, tokens)
        assert np.abs(windowed - full).max() > 1e-3

    def test_flash_shape_splash_matches_dense_window_path(self):
        from paddle_tpu.core import flags as _flags
        from paddle_tpu.models.nlp import LlamaConfig
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 256, (1, 512)).astype(np.int32)

        def build(window):
            cfg = LlamaConfig.tiny(vocab=256, hidden=128, layers=1,
                                   heads=2, kv_heads=1)
            cfg.max_position_embeddings = 512
            cfg.sliding_window = window
            return cfg

        # splash path (flash enabled, D=64 eligible)
        splash_out = self._logits(build(256), tokens)
        # dense window path (flash disabled -> elementwise mask)
        prev = _flags.get_flag("use_flash_attention")
        _flags.set_flags({"use_flash_attention": False})
        try:
            dense_out = self._logits(build(256), tokens)
        finally:
            _flags.set_flags({"use_flash_attention": prev})
        np.testing.assert_allclose(splash_out, dense_out, rtol=2e-4,
                                   atol=2e-4)


class TestGroupedSplash:
    """GQA splash: equivalent to splash over jnp.repeat'ed K/V without
    the repeat; gradients sum over each kv head's G query groups."""

    def _data(self, Hq=4, Hkv=2, S=256, D=64):
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.standard_normal((1, Hq, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, Hkv, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, Hkv, S, D)), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("window", [None, 100])
    def test_matches_repeat_oracle(self, window):
        from paddle_tpu.ops.pallas.splash_attention import (
            grouped_splash_attention)
        q, k, v = self._data()
        G = q.shape[1] // k.shape[1]
        bm = np.tril(np.ones((2, 2), bool))

        def oracle(q, k, v):
            kr = jnp.repeat(k, G, axis=1)
            vr = jnp.repeat(v, G, axis=1)
            return splash_attention(q, kr, vr, bm, True, None, 128, 128,
                                    window)

        out = grouped_splash_attention(q, k, v, bm, True, None, 128, 128,
                                       window)
        ref = oracle(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

        def loss_g(q, k, v):
            return jnp.sum(grouped_splash_attention(
                q, k, v, bm, True, None, 128, 128, window) ** 2)

        def loss_o(q, k, v):
            return jnp.sum(oracle(q, k, v) ** 2)

        gg = jax.grad(loss_g, argnums=(0, 1, 2))(q, k, v)
        go = jax.grad(loss_o, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gg, go, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=f"d{name}")

    def test_llama_gqa_window_uses_grouped_path(self):
        # full-model parity: GQA + sliding_window (grouped splash) vs the
        # dense window path (flash disabled)
        import paddle_tpu as paddle
        from paddle_tpu.core import flags as _flags
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        rng = np.random.default_rng(8)
        tokens = rng.integers(0, 128, (1, 512)).astype(np.int32)

        def logits():
            cfg = LlamaConfig.tiny(vocab=128, hidden=128, layers=1,
                                   heads=2, kv_heads=1)
            cfg.max_position_embeddings = 512
            cfg.sliding_window = 200
            paddle.seed(21)
            m = LlamaForCausalLM(cfg)
            m.eval()
            return m(paddle.to_tensor(tokens)).numpy()

        splash_out = logits()
        prev = _flags.get_flag("use_flash_attention")
        _flags.set_flags({"use_flash_attention": False})
        try:
            dense_out = logits()
        finally:
            _flags.set_flags({"use_flash_attention": prev})
        np.testing.assert_allclose(splash_out, dense_out, rtol=2e-4,
                                   atol=2e-4)

    def test_vmem_budget_raises_and_model_falls_back(self, monkeypatch):
        import paddle_tpu.ops.pallas.splash_attention as sp
        rng = np.random.default_rng(9)
        # MQA G=64: G*128 = 8192 rows > row cap -> explicit error (rows
        # checked first; a v5e-measured scoped-vmem limit, not a guess)
        q = jnp.asarray(rng.standard_normal((1, 64, 256, 8)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 1, 256, 8)), jnp.float32)
        bm = np.tril(np.ones((2, 2), bool))
        with pytest.raises(ValueError, match="VMEM row budget"):
            sp.grouped_splash_attention(q, k, k, bm, True)
        assert not sp.fits_score_budget(64)  # the llama gate predicate
        # score budget binds when rows fit: G=16, bq=128 (rows 2048 ok)
        # but bk=512 -> 16*128*512 = 1M f32 > SCORE_ELEMS
        q2 = jnp.asarray(rng.standard_normal((1, 16, 256, 8)), jnp.float32)
        k2 = jnp.asarray(rng.standard_normal((1, 1, 1024, 8)), jnp.float32)
        bm2 = np.ones((2, 2), bool)
        with pytest.raises(ValueError, match="VMEM score budget"):
            sp.grouped_splash_attention(q2, k2, k2, bm2, False)
        assert not sp.fits_score_budget(16, 128, 512)

        # model-level fallback: with the budget shrunk so even G=2 is
        # over, the GQA windowed model must take the repeat path and
        # still match the dense window oracle (not raise)
        import paddle_tpu as paddle
        from paddle_tpu.core import flags as _flags
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        tokens = rng.integers(0, 128, (1, 256)).astype(np.int32)

        def logits():
            cfg = LlamaConfig.tiny(vocab=128, hidden=128, layers=1,
                                   heads=2, kv_heads=1)
            cfg.max_position_embeddings = 256
            cfg.sliding_window = 100
            paddle.seed(23)
            m = LlamaForCausalLM(cfg)
            m.eval()
            return m(paddle.to_tensor(tokens)).numpy()

        # G=2 over budget, G=1 (the repeat path) still within it
        monkeypatch.setattr(sp, "SCORE_ELEMS", 128 * 128 + 1)
        via_repeat = logits()  # grouped gate now fails -> repeat splash
        monkeypatch.undo()
        prev = _flags.get_flag("use_flash_attention")
        _flags.set_flags({"use_flash_attention": False})
        try:
            dense = logits()
        finally:
            _flags.set_flags({"use_flash_attention": prev})
        np.testing.assert_allclose(via_repeat, dense, rtol=2e-4,
                                   atol=2e-4)


class TestStreamedSplash:
    """K/V-streaming splash kernels (long-sequence mode): live blocks
    stream through the innermost grid dimension via the prefetched
    kv_idx tables — O(block) VMEM, DMA proportional to density. Must be
    bit-exact against the resident kernels (same walk order)."""

    def _run(self, bm, q, kv, window):
        return lambda a, b, c: splash_attention(a, b, c, bm, True, None,
                                                64, 64, window)

    @pytest.mark.parametrize("groups", [1, 2])
    def test_streamed_matches_resident_fwd_bwd(self, groups, monkeypatch):
        import importlib
        sp = importlib.import_module(
            "paddle_tpu.ops.pallas.splash_attention")
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((1, 2 * groups, 256, 64)),
                        jnp.float32)
        kv = jnp.asarray(rng.standard_normal((1, 2, 256, 64)), jnp.float32)
        bm = sp.banded_block_mask(256, 256, 64, 64, 96, causal=True)

        def run():
            f = self._run(bm, q, kv, 96)
            out, vjp = jax.vjp(f, q, kv, kv)
            return (out, *vjp(out))

        monkeypatch.setattr(sp, "_FORCE_STREAM", False)
        ref = run()
        monkeypatch.setattr(sp, "_FORCE_STREAM", True)
        stv = run()
        for a, b in zip(ref, stv):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_streamed_empty_mask_row_outputs_zero(self, monkeypatch):
        import importlib
        sp = importlib.import_module(
            "paddle_tpu.ops.pallas.splash_attention")
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.standard_normal((1, 2, 256, 64)), jnp.float32)
        bm = sp.banded_block_mask(256, 256, 64, 64, 96, causal=True).copy()
        bm[0, :] = False
        monkeypatch.setattr(sp, "_FORCE_STREAM", True)
        out = splash_attention(q, q, q, bm, True, None, 64, 64, 96)
        assert (np.asarray(out)[:, :, :64] == 0).all()

    def test_long_sequence_resolves_to_streaming(self):
        import importlib
        sp = importlib.import_module(
            "paddle_tpu.ops.pallas.splash_attention")
        # resident K/V at Sk=16384, D=128, bf16 = 16M alone: must stream
        assert not sp._resident_fits(512, 512, 16384, 128, 2)
        # the S=2048 bench shape stays resident (status-quo perf)
        assert sp._resident_fits(512, 512, 2048, 128, 2)


class TestPickSplashBlocks:
    """pick_splash_blocks: coarsest tiling the budgets allow (512-block
    banded splash measured 3x the 128-block kernel on chip, PERF.md
    round 4)."""

    def test_mha_picks_512(self):
        from paddle_tpu.ops.pallas.splash_attention import (
            pick_splash_blocks)
        assert pick_splash_blocks(8192, 8192, 1) == (512, 512)

    def test_g4_shrinks_bk_for_score_budget(self):
        from paddle_tpu.ops.pallas.splash_attention import (
            SCORE_ELEMS, pick_splash_blocks)
        bq, bk = pick_splash_blocks(8192, 8192, 4)
        assert 4 * bq * bk <= SCORE_ELEMS
        assert bq == 512  # rows 4*512=2048 still under the row cap

    def test_mqa_g32_respects_row_cap(self):
        from paddle_tpu.ops.pallas.splash_attention import (
            MAX_ROWS, SCORE_ELEMS, pick_splash_blocks)
        bq, bk = pick_splash_blocks(2048, 2048, 32)
        assert 32 * bq <= MAX_ROWS and 32 * bq * bk <= SCORE_ELEMS

    def test_odd_seq_falls_back(self):
        from paddle_tpu.ops.pallas.splash_attention import (
            pick_splash_blocks)
        assert pick_splash_blocks(384, 384, 1) == (128, 128)
