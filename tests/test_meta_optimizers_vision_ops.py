"""Gradient-comm meta-optimizers + vision ops tests."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.core.tensor import Parameter


class TestGradientMerge:
    def test_applies_every_k(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer)
        p = Parameter(np.array([1.0], np.float32))
        gm = GradientMergeOptimizer(optimizer.SGD(0.1, parameters=[p]),
                                    k_steps=2, avg=True)
        (p * 2.0).sum().backward()
        gm.step()
        np.testing.assert_allclose(p.numpy(), [1.0])  # not yet applied
        (p * 2.0).sum().backward()
        gm.step()
        # avg grad = 2 -> p = 1 - 0.1*2
        np.testing.assert_allclose(p.numpy(), [0.8], rtol=1e-6)


class TestDGC:
    def test_sparsifies_and_keeps_residual(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DGCMomentumOptimizer)
        p = Parameter(np.arange(10, dtype=np.float32))
        dgc = DGCMomentumOptimizer(optimizer.SGD(1.0, parameters=[p]),
                                   sparsity=0.8)
        p._grad = paddle.to_tensor(
            np.array([0, 1, 2, 3, 4, 5, 6, 7, 8, 9], np.float32))
        dgc.step()
        # only top-2 grads applied (sparsity 0.8 of 10 -> k=2)
        applied = np.arange(10, dtype=np.float32) - p.numpy()
        assert (applied != 0).sum() == 2
        assert applied[9] == 9 and applied[8] == 8
        # residual holds the rest
        res = np.asarray(dgc._residual[id(p)])
        assert res[7] == 7 and res[9] == 0


class TestVisionOps:
    def test_box_iou(self):
        from paddle_tpu.vision.ops import box_iou
        a = paddle.to_tensor(np.array([[0, 0, 2, 2]], np.float32))
        b = paddle.to_tensor(np.array([[1, 1, 3, 3], [0, 0, 2, 2]],
                                      np.float32))
        iou = box_iou(a, b).numpy()
        np.testing.assert_allclose(iou[0, 0], 1 / 7, rtol=1e-5)
        np.testing.assert_allclose(iou[0, 1], 1.0, rtol=1e-5)

    def test_nms(self):
        from paddle_tpu.vision.ops import nms
        boxes = paddle.to_tensor(np.array(
            [[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]], np.float32))
        scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
        keep = nms(boxes, iou_threshold=0.5, scores=scores)
        assert keep.numpy().tolist() == [0, 2]

    def test_roi_align_shape(self):
        from paddle_tpu.vision.ops import roi_align
        feat = paddle.randn([1, 8, 16, 16])
        rois = paddle.to_tensor(np.array([[0, 0, 8, 8], [4, 4, 12, 12]],
                                         np.float32))
        out = roi_align(feat, rois, None, output_size=4)
        assert out.shape == [2, 8, 4, 4]

    def test_roi_align_constant_feature(self):
        from paddle_tpu.vision.ops import roi_align
        feat = paddle.ones([1, 2, 8, 8])
        rois = paddle.to_tensor(np.array([[1, 1, 5, 5]], np.float32))
        out = roi_align(feat, rois, None, output_size=2)
        np.testing.assert_allclose(out.numpy(), np.ones((1, 2, 2, 2)),
                                   rtol=1e-5)
