"""paddle_tpu.serving.workload + metrics: seeded traces and the
TTFT/TPOT/SLO record — plus the bench-gate contract for the
serving_workload rows (no model needed anywhere here)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.serving import (MetricsCollector, Request, load_trace,
                                merge_traces, save_trace,
                                synthesize_trace, trace_stats)


def test_synthesize_trace_is_deterministic():
    a = synthesize_trace(seed=4, n_requests=12, shared_prefix_frac=0.5,
                         churn_frac=0.4)
    b = synthesize_trace(seed=4, n_requests=12, shared_prefix_frac=0.5,
                         churn_frac=0.4)
    assert a == b
    c = synthesize_trace(seed=5, n_requests=12, shared_prefix_frac=0.5,
                         churn_frac=0.4)
    assert a != c
    # arrivals are sorted and strictly drawn; lengths within bounds
    arr = [r.arrival for r in a]
    assert arr == sorted(arr)
    assert all(4 <= len(r.prompt) for r in a)
    assert all(4 <= r.max_new_tokens <= 16 for r in a)


def test_bursty_arrivals_are_uniform_waves():
    tr = synthesize_trace(seed=1, n_requests=8, arrival="bursty",
                          burst_size=4, prompt_len=(6, 20))
    by_time = {}
    for r in tr:
        by_time.setdefault(r.arrival, []).append(r)
    assert sorted(len(v) for v in by_time.values()) == [4, 4]
    for grp in by_time.values():
        # one shared prompt length per burst: the dense-wave shape
        assert len({len(r.prompt) for r in grp}) == 1
    with pytest.raises(ValueError, match="arrival"):
        synthesize_trace(arrival="tidal")


def test_shared_prefix_and_churn_fields():
    tr = synthesize_trace(seed=2, n_requests=40, shared_prefix_frac=0.5,
                          prefix_len=8, n_prefix_groups=2,
                          churn_frac=0.5, vocab_size=64)
    grouped = [r for r in tr if r.prefix_group is not None]
    assert grouped  # the frac actually fires
    prefixes = {}
    for r in grouped:
        prefixes.setdefault(r.prefix_group, set()).add(r.prompt[:8])
    for g, ps in prefixes.items():
        assert len(ps) == 1  # every member opens with the group prefix
    churned = [r for r in tr if r.cancel_after is not None]
    assert churned
    assert all(1 <= r.cancel_after < r.max_new_tokens for r in churned)
    st = trace_stats(tr)
    assert st["shared_prefix_requests"] == len(grouped)
    assert st["churn_requests"] == len(churned)
    assert st["n_requests"] == 40


def test_trace_jsonl_round_trip(tmp_path):
    tr = synthesize_trace(seed=6, n_requests=10, shared_prefix_frac=0.3,
                          churn_frac=0.3)
    p = str(tmp_path / "trace.jsonl")
    save_trace(p, tr)
    assert load_trace(p) == tr


def test_merge_traces_sorts_and_rejects_dup_rids():
    a = synthesize_trace(seed=1, n_requests=3, rid_prefix="a")
    b = synthesize_trace(seed=2, n_requests=3, rid_prefix="b")
    m = merge_traces(a, b)
    assert [r.arrival for r in m] == sorted(r.arrival for r in m)
    assert len(m) == 6
    with pytest.raises(ValueError, match="duplicate"):
        merge_traces(a, a)


def test_metrics_report_arithmetic():
    """Hand-built event stream -> exact TTFT/TPOT/SLO numbers."""
    m = MetricsCollector()
    # request a: arrives 0, first token at 2, tokens at 3,4 -> done 4
    m.on_arrival("a", 0.0)
    m.on_admit("a", 1.0, "paged")
    m.on_tokens("a", 2.0, 1)
    m.on_tokens("a", 3.0, 1)
    m.on_tokens("a", 4.0, 1)
    m.on_finish("a", 4.0)
    # request b: arrives 1, first token 5, second 9 -> evicted
    m.on_arrival("b", 1.0)
    m.on_admit("b", 4.0, "dense")
    m.on_tokens("b", 5.0, 1)
    m.on_tokens("b", 9.0, 1)
    m.on_finish("b", 9.0, evicted=True)
    m.on_queue_depth(0.0, 2)
    m.on_queue_depth(5.0, 0)
    ra = m.request("a")
    assert ra["ttft"] == 2.0 and ra["tpot"] == 1.0 and ra["e2e"] == 4.0
    rb = m.request("b")
    assert rb["ttft"] == 4.0 and rb["tpot"] == 4.0 and rb["evicted"]
    rep = m.report(slo_ttft=3.0, slo_tpot=2.0)
    assert rep["completed"] == 2 and rep["evicted"] == 1
    assert rep["generated_tokens"] == 5
    assert rep["makespan"] == 9.0
    assert rep["tokens_per_sec"] == pytest.approx(5 / 9.0, abs=1e-3)
    assert rep["ttft_p50"] == 3.0  # median of [2, 4]
    assert rep["slo_ttft_attained"] == 0.5  # a yes, b no
    assert rep["slo_tpot_attained"] == 0.5
    assert rep["queue_depth_max"] == 2
    rec = m.to_record(policy="routed", device="cpu")
    assert rec["bench"] == "serving_workload"
    assert rec["policy"] == "routed" and rec["device"] == "cpu"


def _run_gate(text, tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "BENCH_GATE_SERVING_BASELINE":
           str(tmp_path / "b.json")}
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_gate.py"),
         "serving", "-"], input=text, capture_output=True, text=True,
        timeout=60, cwd=repo, env=env)
    return r.returncode, json.loads(r.stdout.strip().splitlines()[-1])


def _wl_row(policy, tps):
    return json.dumps({"bench": "serving_workload", "policy": policy,
                       "tokens_per_sec": tps, "device": "cpu"})


def test_bench_gate_serving_workload_rows(tmp_path):
    """The gate's serving mode learns the serving_workload family:
    routed >= 0.95x best fixed passes; a >5% loss FAILs naming the
    winner; missing rows FAIL gracefully (a record, not a traceback)."""
    rows = "\n".join([_wl_row("routed", 100.0), _wl_row("dense", 60.0),
                      _wl_row("paged", 98.0)])
    rc, rec = _run_gate(rows + "\n", tmp_path)
    assert rc == 0 and rec["gate"] == "pass"
    assert rec["best_fixed_policy"] == "paged"
    assert rec["routed_vs_best_fixed"] == pytest.approx(100 / 98, .01)

    rows = "\n".join([_wl_row("routed", 80.0), _wl_row("paged", 100.0)])
    rc, rec = _run_gate(rows + "\n", tmp_path)
    assert rc == 1 and rec["gate"] == "FAIL"
    assert "paged" in rec["reason"]

    # routed row absent -> graceful FAIL
    rc, rec = _run_gate(_wl_row("dense", 60.0) + "\n", tmp_path)
    assert rc == 1 and rec["gate"] == "FAIL"
    assert "routed" in rec["reason"]

    # fixed rows absent -> graceful FAIL
    rc, rec = _run_gate(_wl_row("routed", 60.0) + "\n", tmp_path)
    assert rc == 1 and rec["gate"] == "FAIL"
    assert "fixed" in rec["reason"]

    # diverging outputs FAIL even when the ratio would pass
    rows = "\n".join([
        _wl_row("routed", 100.0), _wl_row("paged", 90.0),
        json.dumps({"bench": "serving_workload_summary",
                    "outputs_match": False})])
    rc, rec = _run_gate(rows + "\n", tmp_path)
    assert rc == 1 and "DIVERGING" in rec["reason"]


def test_bench_gate_spec_rows_still_gate(tmp_path):
    """The original spec family keeps working alongside (regression
    guard for the extension)."""
    rc, rec = _run_gate(json.dumps(
        {"bench": "spec_vs_plain_compiled", "n_draft": 4, "ratio": 1.2,
         "output_matches_plain": True}) + "\n", tmp_path)
    assert rc == 0 and rec["gate"] == "pass"
    # both families present: the worse verdict wins AND the final JSON
    # line carries the combined verdict (consumers read the last line —
    # a passing spec record must not mask the failed workload gate)
    rows = "\n".join([
        json.dumps({"bench": "spec_vs_plain_compiled", "n_draft": 4,
                    "ratio": 1.2, "output_matches_plain": True}),
        _wl_row("routed", 50.0), _wl_row("paged", 100.0)])
    r = subprocess.run(
        [sys.executable, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "bench_gate.py"), "serving", "-"],
        input=rows + "\n", capture_output=True, text=True, timeout=60,
        env={**os.environ, "BENCH_GATE_SERVING_BASELINE":
             str(tmp_path / "b2.json")})
    assert r.returncode == 1
    last = json.loads(r.stdout.strip().splitlines()[-1])
    assert last["gate"] == "FAIL" and last["combined"] is True
    assert last["spec_gate"] == "pass"
    assert last["workload_gate"] == "FAIL"


def test_request_json_round_trip():
    r = Request(rid="x", arrival=1.5, prompt=(1, 2, 3),
                max_new_tokens=4, prefix_group=1, cancel_after=2)
    assert Request.from_json(json.loads(json.dumps(r.to_json()))) == r
    r2 = Request(rid="y", arrival=0.0, prompt=(7,), max_new_tokens=1)
    assert Request.from_json(r2.to_json()) == r2
