"""Grouped-query flash attention: kernel oracle + Llama integration.

Exceeds the reference (fused_attention_op.cu predates GQA): K/V stay at
their true head count — no jnp.repeat HBM/VMEM blowup on the flash path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import flags as _flags
from paddle_tpu.ops.pallas.flash_attention_gqa import grouped_flash_attention


def _dense_ref(q, k, v, causal, groups):
    D = q.shape[-1]
    kk = jnp.repeat(k, groups, axis=1)
    vv = jnp.repeat(v, groups, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kk) / np.sqrt(D)
    if causal:
        S = q.shape[2]
        s = jnp.where(np.tril(np.ones((S, S), bool)), s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vv)


class TestGroupedFlashAttention:
    @pytest.mark.parametrize("hq,hkv,causal", [(4, 2, True), (8, 2, False),
                                               (4, 1, True)])
    def test_matches_dense_repeat(self, hq, hkv, causal):
        rng = np.random.default_rng(0)
        S, D = 256, 64
        q = jnp.asarray(rng.standard_normal((2, hq, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, hkv, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, hkv, S, D)), jnp.float32)
        out = grouped_flash_attention(q, k, v, causal)
        ref = _dense_ref(q, k, v, causal, hq // hkv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_dense_repeat(self):
        rng = np.random.default_rng(1)
        S, D, hq, hkv = 256, 64, 4, 2
        q = jnp.asarray(rng.standard_normal((1, hq, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, hkv, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, hkv, S, D)), jnp.float32)
        g = jax.grad(lambda *a: grouped_flash_attention(*a, True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: _dense_ref(*a, True, 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
        # dk/dv keep the true kv head count
        assert g[1].shape == (1, hkv, S, D)

    def test_head_count_mismatch_raises(self):
        q = jnp.zeros((1, 3, 128, 64))
        k = jnp.zeros((1, 2, 128, 64))
        with pytest.raises(ValueError):
            grouped_flash_attention(q, k, k)


class TestLlamaGQAFlashPath:
    def test_llama_logits_flash_vs_dense(self):
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        paddle.seed(0)
        cfg = LlamaConfig.tiny(vocab=97, hidden=256, layers=2, heads=4,
                               kv_heads=2)
        m = LlamaForCausalLM(cfg)
        m.eval()
        tok = paddle.to_tensor(np.random.default_rng(0).integers(
            0, 97, (2, 256)).astype(np.int32))
        old = _flags.get_flag("use_flash_attention")
        try:
            _flags.set_flags({"use_flash_attention": True})
            flash = m(tok).numpy()
            _flags.set_flags({"use_flash_attention": False})
            dense = m(tok).numpy()
        finally:
            _flags.set_flags({"use_flash_attention": old})
        np.testing.assert_allclose(flash, dense, rtol=2e-4, atol=2e-4)


class TestRingAttentionGQA:
    def test_ring_gqa_matches_dense(self):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.ring_attention import ring_attention
        rng = np.random.default_rng(3)
        B, Hq, Hkv, S, D = 2, 4, 2, 64, 16
        q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("sep",))
        out = ring_attention(q, k, v, mesh, axis="sep", causal=True)
        ref = _dense_ref(q, k, v, True, Hq // Hkv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_ring_gqa_grads(self):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.ring_attention import ring_attention
        rng = np.random.default_rng(4)
        B, Hq, Hkv, S, D = 1, 4, 1, 32, 8
        q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("sep",))
        g = jax.grad(lambda *a: jnp.sum(
            ring_attention(*a, mesh, axis="sep", causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(_dense_ref(*a, True, 4) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
        assert g[1].shape == (B, Hkv, S, D)


class TestFlashUnderTensorParallel:
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_no_allgather_around_pallas_call(self, kv_heads):
        """GSPMD can't partition a Pallas custom call: without the
        shard_map wrap, TP meshes all-gather full Q/K/V around every
        flash call (measured 27MB/step on this tiny config). The wrap
        must eliminate every all-gather and keep loss parity with the
        single-device step."""
        import re
        from jax.sharding import Mesh
        from paddle_tpu.core import flags as _flags
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp.llama import llama_train_step_factory
        import paddle_tpu as paddle

        old = _flags.get_flag("use_flash_attention")
        _flags.set_flags({"use_flash_attention": True})
        try:
            cfg = LlamaConfig.tiny(vocab=128, hidden=256, layers=1,
                                   heads=4, kv_heads=kv_heads)
            rng = np.random.default_rng(0)
            tok = jnp.asarray(rng.integers(0, 128, (4, 256)), jnp.int32)

            paddle.seed(0)
            m1 = LlamaForCausalLM(cfg)
            mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("data",))
            p1, o1, step1, _ = llama_train_step_factory(m1, mesh1,
                                                        remat=False)
            _, _, ref_loss = step1(p1, o1, tok, tok)

            paddle.seed(0)
            m = LlamaForCausalLM(cfg)
            mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                        ("data", "model"))
            params, opt, step, _ = llama_train_step_factory(m, mesh,
                                                            remat=False)
            compiled = step.lower(params, opt, tok, tok).compile()
            _, _, loss = compiled(params, opt, tok, tok)
            np.testing.assert_allclose(float(loss), float(ref_loss),
                                       rtol=2e-5)
            hlo = compiled.as_text()
            n = sum(1 for line in hlo.splitlines()
                    if re.search(r"=\s+\w+\[[\d,]*\]\S*\s+all-gather",
                                 line))
            assert n == 0, f"{n} all-gathers around the flash call"
        finally:
            _flags.set_flags({"use_flash_attention": old})


class TestShardMappedFusedCE:
    def test_fused_ce_data_sep_manual_matches_dense(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.ops.pallas.fused_ce import causal_lm_loss
        rng = np.random.default_rng(0)
        B, S, V = 4, 32, 128
        logits = jnp.asarray(rng.normal(0, 1, (B, S, V)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("data", "sep"))
        manual = ["data", "sep"]

        def _fused(lg, lb):
            loss = causal_lm_loss(lg, lb)
            for a in manual:
                loss = jax.lax.pmean(loss, a)
            return loss

        fn = jax.shard_map(_fused, mesh=mesh,
                           in_specs=(P("data", "sep", None),
                                     P("data", "sep")),
                           out_specs=P(), check_vma=False,
                           axis_names=frozenset(manual))
        dense = jnp.mean(-jnp.take_along_axis(
            jax.nn.log_softmax(logits, -1), labels[..., None], -1)[..., 0])
        np.testing.assert_allclose(float(fn(logits, labels)), float(dense),
                                   rtol=1e-6)
        g1 = jax.grad(lambda lg: fn(lg, labels))(logits)
        g2 = jax.grad(lambda lg: jnp.mean(-jnp.take_along_axis(
            jax.nn.log_softmax(lg, -1),
            labels[..., None], -1)[..., 0]))(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-6)


class TestFlashInPipelineFactory:
    def test_4d_factory_flash_nested_shard_map_parity(self):
        """Inside the 4D factory's partial-manual pipeline the 'model'
        axis is AUTO — the stage body must nest a shard_map around the
        Pallas flash call (GSPMD would all-gather Q/K/V per microbatch
        otherwise) and match the dense path exactly."""
        from jax.sharding import Mesh
        import paddle_tpu as paddle
        from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.nlp import llama_functional as LF

        rng = np.random.default_rng(0)
        tok = jnp.asarray(rng.integers(0, 128, (4, 256)), jnp.int32)
        losses = {}
        from paddle_tpu.parallel import pallas_sharding as PS
        for force in (False, True):
            LF._FORCE_FLASH_FOR_TESTS = force
            PS.ENGAGED["flag"] = False
            try:
                paddle.seed(0)
                # kv_heads=2 exercises the grouped (GQA) kernel branch
                cfg = LlamaConfig.tiny(vocab=128, hidden=256, layers=4,
                                       heads=4, kv_heads=2)
                m = LlamaForCausalLM(cfg)
                mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(
                    1, 2, 2, 2), ("data", "pipe", "sharding", "model"))
                p, o, step = LF.llama_4d_train_step_factory(
                    m, mesh, n_microbatches=2, remat=False)
                p, o, loss = step(p, o, tok, tok)
                # second step covers the backward through the nested
                # shard_map: a wrong dQ/dK/dV would diverge the params
                p, o, loss2 = step(p, o, tok, tok)
                losses[force] = (float(loss), float(loss2))
                if force:
                    assert PS.ENGAGED["flag"], \
                        "nested shard_map branch did not engage"
            finally:
                LF._FORCE_FLASH_FOR_TESTS = False
        np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5)


class TestSdpaUnderMesh:
    def test_sdpa_flash_model_axis_manual(self):
        """scaled_dot_product_attention's flash path must shard_map over
        an AUTO 'model' mesh axis (GSPMD can't partition Pallas) and
        match the plain call exactly."""
        from jax.sharding import Mesh
        import paddle_tpu.nn.functional as F
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.jax_compat import set_mesh

        from paddle_tpu.parallel import pallas_sharding as PS
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))
        rng = np.random.default_rng(0)
        q = rng.standard_normal((2, 256, 4, 64)).astype(np.float32)

        def run(qv):
            out = F.scaled_dot_product_attention(
                Tensor(qv), Tensor(qv), Tensor(qv), is_causal=True,
                use_pallas=True)
            return out._value

        PS.ENGAGED["flag"] = False
        # jax_compat.set_mesh: jax.sharding.set_mesh on new jax; a compat
        # context the pallas-sharding probe reads on 0.4.x images
        with set_mesh(mesh):
            sharded = jax.jit(run)(jnp.asarray(q))
        assert PS.ENGAGED["flag"], "manual shard_map path did not engage"
        plain = run(jnp.asarray(q))
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(plain),
                                   atol=2e-5)


class TestGQALongContextDelegation:
    """Past the resident-K/V frontier, grouped_flash_attention delegates
    to the K/V-streaming splash kernels (full causal block mask) instead
    of failing to compile. Must be bit-exact vs the grouped core."""

    @pytest.mark.parametrize("G,S", [(2, 256), (4, 512), (8, 512)])
    def test_delegation_matches_core(self, G, S, monkeypatch):
        # G=4/8 at 512-divisible S are the realistic Llama-3 delegation
        # configs: naive 512x512 splash blocks would be REJECTED by the
        # score/row budgets — the wrapper must shrink group-aware
        import importlib
        ga = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention_gqa")
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.standard_normal((1, 2 * G, S, 64)),
                        jnp.float32)
        kv = jnp.asarray(rng.standard_normal((1, 2, S, 64)), jnp.float32)

        def run():
            f = lambda a, b, c: ga.grouped_flash_attention(a, b, c, True)
            out, vjp = jax.vjp(f, q, kv, kv)
            return (out, *vjp(out))

        ref = run()

        def reject(*a, **k):
            raise ga.ResidentOverflowError("test-forced")
        monkeypatch.setattr(ga, "_gqa_resolve_blocks", reject)
        got = run()
        for a, b in zip(ref, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_pinned_blocks_do_not_delegate(self, monkeypatch):
        import importlib
        ga = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention_gqa")
        rng = np.random.default_rng(12)
        q = jnp.asarray(rng.standard_normal((1, 4, 256, 64)), jnp.float32)
        kv = jnp.asarray(rng.standard_normal((1, 2, 256, 64)),
                         jnp.float32)
        called = []
        orig = ga._grouped_flash_core

        def spy(*a, **k):
            called.append(1)
            return orig(*a, **k)
        monkeypatch.setattr(ga, "_grouped_flash_core", spy)
        ga.grouped_flash_attention(q, kv, kv, True, None, 128, 128)
        assert called  # pinned blocks go straight to the core kernel

    def test_resolver_raises_typed_error_at_extreme_s(self):
        import importlib
        ga = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention_gqa")
        with pytest.raises(ga.ResidentOverflowError):
            ga._gqa_resolve_blocks(16384, 16384, 4, None, None)
