"""fft/signal, quantization, auto_parallel annotation tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn


class TestFFT:
    def test_fft_roundtrip(self):
        from paddle_tpu import fft
        x = paddle.to_tensor(np.random.randn(16).astype(np.float32))
        X = fft.fft(x)
        back = fft.ifft(X)
        np.testing.assert_allclose(back.numpy().real, x.numpy(), atol=1e-5)

    def test_rfft_shapes(self):
        from paddle_tpu import fft
        x = paddle.to_tensor(np.random.randn(4, 32).astype(np.float32))
        X = fft.rfft(x)
        assert X.shape == [4, 17]
        back = fft.irfft(X)
        np.testing.assert_allclose(back.numpy(), x.numpy(), atol=1e-5)

    def test_fft_matches_numpy(self):
        from paddle_tpu import fft
        x = np.random.randn(8).astype(np.float32)
        out = fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)

    def test_stft_istft_roundtrip(self):
        from paddle_tpu import signal
        x = paddle.to_tensor(np.random.randn(1, 512).astype(np.float32))
        win = paddle.to_tensor(np.hanning(128).astype(np.float32))
        spec = signal.stft(x, n_fft=128, hop_length=32, window=win)
        assert spec.shape[1] == 65
        rec = signal.istft(spec, n_fft=128, hop_length=32, window=win,
                           length=512)
        # center-padded regions reconstruct well away from edges
        np.testing.assert_allclose(rec.numpy()[0, 64:-64],
                                   x.numpy()[0, 64:-64], atol=1e-3)


class TestQuantization:
    def test_fake_quant_forward_and_ste_grad(self):
        from paddle_tpu.quantization import fake_quantize_dequantize
        x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32),
                             stop_gradient=False)
        s = paddle.to_tensor(1.0)
        out = fake_quantize_dequantize(x, s, bits=8)
        assert np.abs(out.numpy() - x.numpy()).max() < 1 / 127 + 1e-6
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(11), rtol=1e-6)

    def test_qat_swaps_layers_and_trains(self):
        from paddle_tpu.quantization import ImperativeQuantAware, QuantedLinear
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        qat = ImperativeQuantAware()
        qnet = qat.quantize(net)
        assert isinstance(qnet[0], QuantedLinear)
        x = paddle.randn([4, 8])
        out = qnet(x)
        assert out.shape == [4, 4]
        out.sum().backward()
        assert qnet[0].inner.weight.grad is not None

    def test_ptq(self, tmp_path):
        from paddle_tpu.io import TensorDataset, DataLoader
        from paddle_tpu.quantization import PostTrainingQuantization
        net = nn.Sequential(nn.Linear(8, 4))
        data = DataLoader(TensorDataset(
            [np.random.randn(32, 8).astype(np.float32)]), batch_size=8)
        ptq = PostTrainingQuantization(net, data)
        ptq.quantize()
        state = ptq.save_quantized_model(str(tmp_path / "q"))
        keys = [k for k in state if k.endswith("weight_int8")]
        assert keys and state[keys[0]].dtype == np.int8


class TestAutoParallel:
    def test_process_mesh_and_shard_tensor(self):
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          shard_tensor)
        mesh = ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                           dim_names=["x", "y"])
        assert mesh.shape == [2, 4]
        t = shard_tensor(paddle.randn([8, 16]), mesh, ["x", None])
        assert t.sharding_spec == jax.sharding.PartitionSpec("x", None)
        # actually sharded over devices
        assert len(t._value.sharding.device_set) >= 2

    def test_shard_op_in_jit(self):
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          shard_op)
        from paddle_tpu.core.tensor import Tensor
        mesh = ProcessMesh(np.arange(8).tolist(), dim_names=["x"])

        def matmul_op(a, b):
            return paddle.matmul(a, b)
        sharded_mm = shard_op(matmul_op, mesh, out_shard_specs=[["x", None]])

        def f(av, bv):
            return sharded_mm(Tensor(av), Tensor(bv))._value
        a = jnp.ones((8, 4))
        b = jnp.ones((4, 4))
        with mesh.jax_mesh():
            out = jax.jit(f)(a, b)
        np.testing.assert_allclose(np.asarray(out), 4 * np.ones((8, 4)))

    def test_engine_fit(self):
        from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh
        from paddle_tpu.io import TensorDataset
        from paddle_tpu import optimizer
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        eng = Engine(net, nn.CrossEntropyLoss(),
                     optimizer.Adam(1e-2, parameters=net.parameters()))
        mesh = ProcessMesh(np.arange(8).tolist(), dim_names=["data"])
        eng.prepare(process_mesh=mesh)
        x = np.random.randn(32, 4).astype(np.float32)
        y = np.random.randint(0, 2, 32).astype(np.int64)
        eng.fit(TensorDataset([x, y]), epochs=1, batch_size=8)
        assert eng.cost()["total_params"] > 0
