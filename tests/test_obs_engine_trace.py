"""End-to-end observability through the serving engine: trace schema
(valid chrome JSON, same-track spans nest, every completed/shed
request closes its root span on BOTH backends), zero-span + identical
results when tracing is off, the engine-log JSONL round trip, and
tools/trace_report.py over a real export.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.serving import (QoSScheduler, Request, ServingEngine,
                                load_engine_log,
                                synthesize_overload_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def srv_model():
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    srv = llama_serving_decode_factory(model, max_len=48, page_size=8,
                                       n_pool_pages=25, batch_capacity=4,
                                       chunked_prefill=8)
    return srv


def _trace(seed=5, n=5, cancel=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        prompt = tuple(int(t) for t in rng.integers(1, 97, 6))
        out.append(Request(rid=f"r{i}", arrival=0.5 * i, prompt=prompt,
                           max_new_tokens=2 + i,
                           cancel_after=cancel if i == n - 1 else None))
    return out


def _engine(srv, policy, **kw):
    kw.setdefault("clock", "fixed")
    return ServingEngine(serving=srv, slots=4, policy=policy, **kw)


def _chrome(res):
    return res.trace.to_chrome()["traceEvents"]


def _roots(evts):
    opened = [e["id"] for e in evts if e["ph"] == "b"]
    closed = [e["id"] for e in evts if e["ph"] == "e"]
    return opened, closed


@pytest.mark.parametrize("policy", ["paged", "dense"])
def test_root_span_closed_per_request_both_backends(srv_model, policy):
    """Every request (completed or evicted) opens exactly one root and
    closes it, on the paged AND dense backends; outcomes ride the
    closing event."""
    trace = _trace(cancel=1)
    res = _engine(srv_model, policy, trace=obs.Tracer()).run(trace)
    evts = _chrome(res)
    opened, closed = _roots(evts)
    assert sorted(opened) == sorted(r.rid for r in trace)
    assert sorted(closed) == sorted(opened)  # no dangling roots
    ends = {e["id"]: e["args"] for e in evts if e["ph"] == "e"}
    assert ends["r4"]["outcome"] == "cancel"  # the churned request
    done = [r for r in trace if r.rid != "r4"]
    assert all(ends[r.rid]["outcome"] == "completed" for r in done)
    assert all("n_tokens" in a for a in ends.values())


@pytest.mark.parametrize("policy", ["paged", "dense"])
def test_same_track_spans_nest(srv_model, policy):
    """Chrome renders same-tid X spans as a stack: any two must be
    disjoint or contained, never partially overlapping."""
    res = _engine(srv_model, policy, trace=obs.Tracer()).run(_trace())
    evts = _chrome(res)
    by_tid = {}
    for e in evts:
        if e["ph"] == "X":
            by_tid.setdefault(e["tid"], []).append(
                (e["ts"], e["ts"] + e["dur"]))
    assert by_tid, "no spans recorded"
    for tid, spans in by_tid.items():
        spans.sort()
        for (a0, a1), (b0, b1) in zip(spans, spans[1:]):
            disjoint = b0 >= a1 - 1e-6
            contained = b1 <= a1 + 1e-6
            assert disjoint or contained, (tid, (a0, a1), (b0, b1))


def test_trace_is_valid_chrome_json_with_tracks(srv_model, tmp_path):
    p = tmp_path / "t.json"
    res = _engine(srv_model, "paged", trace=str(p)).run(_trace())
    assert res.trace is not None
    d = json.loads(p.read_text())  # export happened, parses
    evts = d["traceEvents"]
    tracks = {e["args"]["name"] for e in evts
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    # the contract: one track per decode slot used, one per tenant
    # cohort (plain trace -> "requests"), engine + jit + scheduler axes
    assert "requests" in tracks and "engine" in tracks
    assert any(t.startswith("slot/") for t in tracks)
    for e in evts:
        assert {"name", "ph", "pid", "tid"} <= set(e)
    # slot occupancy spans: acquire/release pairs from the slot log
    slot_tids = {e["tid"] for e in evts if e.get("ph") == "M"
                 and e["name"] == "thread_name"
                 and e["args"]["name"].startswith("slot/")}
    occ = [e for e in evts if e["ph"] == "X" and e["tid"] in slot_tids]
    releases = [s for s in res.slot_log if s[1] == "release"]
    assert len(occ) == len(releases)


def test_tracing_off_is_zero_span_and_byte_identical(srv_model):
    """trace=None (the default): no tracer exists, nothing records —
    and outputs/slot_log/metrics are byte-identical to a traced run
    (observability must never change behavior)."""
    trace = _trace(cancel=1)
    base = _engine(srv_model, "paged").run(trace)
    assert base.trace is None
    # a bystander tracer activated OUTSIDE the engine sees nothing
    # from a trace=None run: the engine's obs path is fully off
    t = obs.Tracer(clock=lambda: 0.0)
    with obs.use(t):
        again = _engine(srv_model, "paged").run(trace)
    assert len(t) == 0
    traced = _engine(srv_model, "paged", trace=obs.Tracer()).run(trace)
    assert len(traced.trace) > 0
    for res in (again, traced):
        assert res.outputs == base.outputs
        assert res.slot_log == base.slot_log
        assert res.decisions == base.decisions
        assert res.report() == base.report()


def test_qos_run_traces_sheds_and_closes_their_roots(srv_model):
    trace = synthesize_overload_trace(
        seed=0, n_requests=24, service_tokens_per_unit=4.0,
        prompt_len=(4, 10), output_len=(4, 10), vocab_size=97)
    sched = QoSScheduler(tenant_weights={"intl": 2.0, "std": 1.0,
                                         "bulk": 0.5})
    res = _engine(srv_model, "paged", scheduler=sched,
                  trace=obs.Tracer()).run(trace)
    assert res.shed, "overload trace must shed for this test to bite"
    evts = _chrome(res)
    opened, closed = _roots(evts)
    assert sorted(opened) == sorted(r.rid for r in trace)
    assert sorted(closed) == sorted(opened)
    ends = {e["id"]: e["args"] for e in evts if e["ph"] == "e"}
    sheds = [e for e in evts if e["ph"] == "i" and e["name"] == "shed"]
    assert {s["args"]["rid"] for s in sheds} == set(res.shed)
    for rid, reason in res.shed.items():
        assert ends[rid]["outcome"] == "shed"
        assert ends[rid]["reason"] == reason
    for s in sheds:  # reason + tenant ride the scheduler instant
        assert s["args"]["reason"] and "tenant" in s["args"]
    # tenant tracks exist (one per tenant in the trace)
    tracks = {e["args"]["name"] for e in evts
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"tenant/intl", "tenant/std", "tenant/bulk"} <= tracks
    # wave decisions carry the routing rule
    waves = [e for e in evts if e["ph"] == "i" and e["name"] == "wave"]
    assert waves and all("rule" in e["args"] for e in waves)


def test_engine_log_jsonl_round_trip(srv_model, tmp_path):
    trace = _trace(cancel=1)
    res = _engine(srv_model, "paged").run(trace)
    p = tmp_path / "engine_log.jsonl"
    res.save_log(str(p))
    log = load_engine_log(str(p))
    assert log["decisions"] == res.decisions
    assert log["slot_log"] == res.slot_log  # tuples restored
    assert log["shed"] == res.shed
    assert log["meta"]["policy"] == res.policy
    assert log["meta"]["pages_total"] == res.pages_total
    # QoS run: sheds round-trip too
    otrace = synthesize_overload_trace(
        seed=0, n_requests=24, service_tokens_per_unit=4.0,
        prompt_len=(4, 10), output_len=(4, 10), vocab_size=97)
    res2 = _engine(srv_model, "paged",
                   scheduler=QoSScheduler()).run(otrace)
    res2.save_log(str(p))
    log2 = load_engine_log(str(p))
    assert log2["shed"] == res2.shed
    assert log2["meta"]["scheduler"] == "qos"


def test_trace_report_summarizes_engine_export(srv_model, tmp_path):
    p = tmp_path / "t.json"
    trace = _trace()
    _engine(srv_model, "paged", trace=str(p)).run(trace)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(p), "--json"], capture_output=True, text=True, timeout=60,
        cwd=REPO)
    assert r.returncode == 0, r.stderr[-500:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["bench"] == "trace_report"
    assert rec["requests"] == len(trace)
    assert rec["open_roots"] == []
    assert rec["outcomes"].get("completed") == len(trace)
    assert rec["slot_occupancy"]  # per-slot busy fractions present
    # human mode renders the waterfall without crashing
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(p)], capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r2.returncode == 0
    assert "waterfall" in r2.stdout and "slot occupancy" in r2.stdout
    # graceful FAIL on a non-trace file
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    r3 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(bad)], capture_output=True, text=True, timeout=60,
        cwd=REPO)
    assert r3.returncode == 1
    assert json.loads(r3.stdout.strip().splitlines()[-1]).get("error")


def test_jit_compile_events_recorded_cold(srv_model):
    """A COLD engine (fresh factory) records jit.compile instants for
    the programs its first run compiles, and the serving compile
    counter moves."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    srv = llama_serving_decode_factory(model, max_len=48, page_size=8,
                                       n_pool_pages=25,
                                       batch_capacity=4,
                                       chunked_prefill=8)
    c = obs.REGISTRY.counter("serving_jit_compiles_total")
    before = c.value
    res = ServingEngine(serving=srv, slots=4, policy="paged",
                        clock="fixed", trace=obs.Tracer()).run(_trace())
    evts = _chrome(res)
    compiles = [e for e in evts if e["ph"] == "i"
                and e["name"] == "jit.compile"]
    assert compiles, "cold run recorded no compile events"
    assert all(e["args"]["wall_s"] > 0 for e in compiles)
    sites = {e["args"]["site"] for e in compiles}
    assert sites & {"prefill", "decode"}
    assert c.value > before
    # and the metrics registry exposes cleanly after all of it
    assert "serving_jit_compiles_total" in obs.REGISTRY.expose_text()


def test_compile_counter_live_without_tracing():
    """The obs contract: counters record even when no trace does — a
    COLD trace=None run still moves serving_jit_compiles_total (only
    the registry kill-switch stops it)."""
    from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.nlp.llama_decode import (
        llama_serving_decode_factory)
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=2, heads=4,
                           kv_heads=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    srv = llama_serving_decode_factory(model, max_len=48, page_size=8,
                                       n_pool_pages=25,
                                       batch_capacity=4,
                                       chunked_prefill=8)
    c = obs.REGISTRY.counter("serving_jit_compiles_total")
    before = c.value
    ServingEngine(serving=srv, slots=4, policy="paged",
                  clock="fixed").run(_trace())
    assert c.value > before


def test_metrics_collector_publish_derived_view(srv_model):
    res = _engine(srv_model, "paged").run(_trace())
    reg = obs.MetricsRegistry()
    rec = res.metrics.publish(registry=reg, prefix="sr")
    assert rec == res.report()  # publishing IS the unchanged report
    snap = reg.snapshot()
    assert snap["sr_completed"] == rec["completed"]
    assert snap["sr_generated_tokens"] == rec["generated_tokens"]
    assert "sr_ttft_p50" in snap
