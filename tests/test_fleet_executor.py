"""FleetExecutor actor runtime tests (carrier/interceptor/message-bus
pipeline + DistModel inference entry)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet_executor import (
    Carrier, DistModel, DistModelConfig, FleetExecutor, MessageBus, TaskNode)


class TestCarrier:
    def test_linear_pipeline_order_preserved(self):
        log = []

        def s1(x):
            return x + 1

        def s2(x):
            log.append(x)
            return x * 10
        exe = FleetExecutor([s1, s2])
        out = exe.run([1, 2, 3, 4])
        assert out == [20, 30, 40, 50]

    def test_single_stage(self):
        exe = FleetExecutor([lambda x: x * 2])
        assert exe.run([5]) == [10]

    def test_error_propagates(self):
        def boom(x):
            raise RuntimeError("stage failed")
        exe = FleetExecutor([lambda x: x, boom])
        import pytest
        with pytest.raises(RuntimeError, match="stage failed"):
            exe.run([1, 2])

    def test_jax_stages_overlap(self):
        import jax
        import jax.numpy as jnp
        w1 = jnp.ones((32, 32)) * 0.01
        w2 = jnp.ones((32, 32)) * 0.02
        s1 = jax.jit(lambda x: jnp.tanh(x @ w1))
        s2 = jax.jit(lambda x: x @ w2)
        exe = FleetExecutor([s1, s2])
        mbs = [jnp.ones((4, 32)) * i for i in range(4)]
        outs = exe.run(mbs)
        ref = [np.asarray(s2(s1(m))) for m in mbs]
        for got, want in zip(outs, ref):
            np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


class TestDistModel:
    def test_pipelined_inference_matches_direct(self):
        net = paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
            paddle.nn.Linear(16, 16), paddle.nn.ReLU(),
            paddle.nn.Linear(16, 4))
        x = paddle.to_tensor(np.random.rand(10, 8).astype(np.float32))
        direct = net(x).numpy()
        dm = DistModel(DistModelConfig(model=net, n_microbatches=3),
                       n_stages=2)
        got = dm.run(x).numpy()
        np.testing.assert_allclose(got, direct, rtol=1e-5)


class TestDistributedPasses:
    def test_registry_and_manager(self):
        from paddle_tpu.distributed import passes as dp
        ctx = dp.PassContext()
        mgr = dp.PassManager([
            dp.new_pass("auto_parallel_amp", {"dtype": "bfloat16"}),
            dp.new_pass("auto_parallel_recompute"),
            dp.new_pass("auto_parallel_gradient_merge", {"k_steps": 8}),
            dp.new_pass("fuse_all_reduce", {"fuse_grad_size_in_MB": 64}),
        ])
        mgr.apply(ctx)
        assert ctx.strategy.amp
        assert ctx.strategy.recompute
        assert ctx.strategy.gradient_merge
        assert ctx.strategy.gradient_merge_configs["k_steps"] == 8
        assert ctx.strategy.fuse_grad_size_in_MB == 64
        assert ctx.applied == ["auto_parallel_amp",
                               "auto_parallel_recompute",
                               "auto_parallel_gradient_merge",
                               "fuse_all_reduce"]

    def test_sharding_pass_marks_optimizer(self):
        from paddle_tpu.distributed import passes as dp
        import paddle_tpu.optimizer as popt
        net = paddle.nn.Linear(4, 4)
        opt = popt.AdamW(1e-3, parameters=net.parameters())
        ctx = dp.PassContext(model=net, optimizer=opt)
        dp.new_pass("auto_parallel_sharding", {"stage": 3,
                                               "degree": 4}).apply(ctx)
        assert ctx.strategy.sharding
        assert opt._shard_states_axis == "sharding"
        assert any(getattr(p, "sharding_spec", None) is not None
                   for p in net.parameters())

    def test_unknown_pass(self):
        from paddle_tpu.distributed import passes as dp
        import pytest
        with pytest.raises(KeyError):
            dp.new_pass("not_a_pass")
