"""Swin Transformer. ~ PaddleClas swin_transformer.py."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.models import SwinTransformer
from paddle_tpu.vision.models.swin import (_window_partition,
                                           _window_reverse)


def _tiny(classes=5, img=32):
    return SwinTransformer(img_size=img, patch_size=4, class_num=classes,
                           embed_dim=16, depths=(2, 2), num_heads=(2, 4),
                           window=4)


def test_window_partition_roundtrip():
    x = paddle.randn([2, 8, 8, 3])
    w = _window_partition(x, 4)
    assert w.shape == [2 * 4, 16, 3]
    back = _window_reverse(w, 4, 8, 8)
    np.testing.assert_allclose(back.numpy(), x.numpy())


def test_forward_shape_and_shift_mask():
    net = _tiny()
    net.eval()
    out = net(paddle.randn([2, 3, 32, 32]))
    assert out.shape == [2, 5]
    assert np.isfinite(out.numpy()).all()
    # stage 1's second block is shifted with a precomputed additive
    # mask; stage 2's window covers the whole 4x4 map so its shift
    # correctly degrades to 0
    shifted = net.stages[0][1]
    assert shifted.shift == 2
    m = shifted.attn_mask.numpy()
    assert set(np.unique(m)) == {-100.0, 0.0}
    assert net.stages[1][1].shift == 0


def test_hierarchy_dims():
    net = _tiny()
    # after one merge: dim doubles, resolution halves
    assert net.stages[0][0].dim == 16
    assert net.stages[1][0].dim == 32
    assert net.stages[1][0].resolution == (4, 4)


def test_train_step_learns():
    paddle.seed(0)
    net = _tiny(classes=3)
    opt = paddle.optimizer.AdamW(parameters=net.parameters(),
                                 learning_rate=1e-3)
    rng = np.random.default_rng(0)
    temp = rng.normal(0, 1, (3, 3, 32, 32)).astype(np.float32)
    y = rng.integers(0, 3, 18)
    x = (temp[y] + 0.1 * rng.normal(0, 1, (18, 3, 32, 32))
         ).astype(np.float32)
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y.astype(np.int64))
    first = None
    for _ in range(10):
        loss = paddle.nn.functional.cross_entropy(net(xt), yt)
        if first is None:
            first = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first * 0.6, (first, float(loss))
