"""Scan-over-stacked-layers decode programs (round-5 verdict item 3).

The decode factories run one ``lax.scan`` layer body over stacked
(L, ...) weights; ``scan_layers=False`` unrolls the layers. Both paths
must be TOKEN-EXACT equal (same math, different program structure), the
scan program must be materially smaller, and — the 0.44B compile fix —
the speculative programs must carry weights as jit ARGUMENTS, never as
closure constants inlined into the lowered module.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.nlp import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.nlp.llama_decode import (
    llama_decode_factory, llama_paged_decode_factory,
    llama_speculative_decode_factory)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    cfg = LlamaConfig.tiny(vocab=97, hidden=32, layers=4, heads=4,
                           kv_heads=2)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompt():
    return np.random.default_rng(0).integers(0, 97, (2, 6)).astype(
        np.int32)


def test_stack_unstack_roundtrip(model):
    from paddle_tpu.models.nlp.llama_functional import (
        split_params, stack_layers, unstack_layers)
    _, layers = split_params(model)
    per = unstack_layers(layers)
    assert len(per) == model.config.num_hidden_layers
    back = stack_layers(per)
    for k in layers:
        np.testing.assert_array_equal(np.asarray(layers[k]),
                                      np.asarray(back[k]))


class TestDenseParity:
    def test_generate_and_compiled_token_exact(self, model, prompt):
        gen_s = llama_decode_factory(model, max_len=48, scan_layers=True)
        gen_u = llama_decode_factory(model, max_len=48,
                                     scan_layers=False)
        a = np.asarray(gen_s(jnp.asarray(prompt), max_new_tokens=12))
        b = np.asarray(gen_u(jnp.asarray(prompt), max_new_tokens=12))
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(gen_s.compiled(prompt, 12),
                                      gen_u.compiled(prompt, 12))

    def test_int8_cache_parity(self, model, prompt):
        gen_s = llama_decode_factory(model, max_len=48,
                                     kv_cache_dtype="int8")
        gen_u = llama_decode_factory(model, max_len=48,
                                     kv_cache_dtype="int8",
                                     scan_layers=False)
        a = np.asarray(gen_s(jnp.asarray(prompt), max_new_tokens=10))
        b = np.asarray(gen_u(jnp.asarray(prompt), max_new_tokens=10))
        np.testing.assert_array_equal(a, b)

    def test_scan_program_smaller(self, model):
        """The whole point of the stacking: the unrolled decode step
        lowers every layer's body; the scan variant lowers ONE."""
        sizes = {}
        for flag in (True, False):
            gen = llama_decode_factory(model, max_len=32,
                                       scan_layers=flag)
            p = gen._parts
            tok = jnp.zeros((1,), jnp.int32)
            kc = p["init_caches"](1, jnp.float32)
            vc = p["init_caches"](1, jnp.float32)
            low = p["decode_step"].lower(p["outer"], p["layers"], tok,
                                         jnp.asarray(4), kc, vc)
            sizes[flag] = len(low.as_text())
        # at L=4 the layer part dominates: unrolled must be well over
        # the scan size (exact ratio drifts with jax versions)
        assert sizes[False] > 1.5 * sizes[True], sizes


class TestSpeculativeParity:
    def _models(self):
        paddle.seed(31)
        t = LlamaForCausalLM(LlamaConfig.tiny(
            vocab=97, hidden=64, layers=3, heads=4, kv_heads=2))
        t.eval()
        paddle.seed(32)
        d = LlamaForCausalLM(LlamaConfig.tiny(
            vocab=97, hidden=32, layers=1, heads=2, kv_heads=2))
        d.eval()
        return t, d

    def test_compiled_spec_scan_vs_unrolled_vs_oracle(self):
        t, d = self._models()
        prompt = np.asarray(
            np.random.default_rng(2).integers(0, 97, (1, 6)), np.int32)
        oracle = np.asarray(llama_decode_factory(t, max_len=64)(
            prompt, max_new_tokens=20))
        spec_s = llama_speculative_decode_factory(t, d, max_len=64,
                                                  n_draft=4)
        spec_u = llama_speculative_decode_factory(t, d, max_len=64,
                                                  n_draft=4,
                                                  scan_layers=False)
        got_s = spec_s.compiled(prompt, max_new_tokens=20)
        got_u = spec_u.compiled(prompt, max_new_tokens=20)
        np.testing.assert_array_equal(got_s, got_u)
        # greedy spec == the target's greedy generation, both paths
        np.testing.assert_array_equal(got_s, oracle)

    def test_spec_module_carries_no_weight_constants(self):
        """THE 0.44B compile fix: weights travel as jit arguments.  A
        closed-over array lowers as an inline literal, so the two-model
        module used to scale with model bytes (~1 GB at 0.44B — what
        actually broke the remote compile service); as arguments the
        module stays ~100 KB at ANY model size. Pin the property by
        asserting the lowered module text is a small fraction of the
        weight bytes it would otherwise embed."""
        t, d = self._models()
        spec = llama_speculative_decode_factory(t, d, max_len=64,
                                                n_draft=4)
        sp = spec._parts
        tokens = jnp.zeros((1, 6), jnp.int32)
        state = jax.eval_shape(sp["spec_prefill"], sp["params"], tokens)
        low = sp["spec_chunk"].lower(sp["params"], state, 4,
                                     jnp.asarray(20, jnp.int32))
        module_bytes = len(low.as_text())
        weight_bytes = sum(
            leaf.size * leaf.dtype.itemsize for leaf in
            jax.tree_util.tree_leaves(sp["params"]))
        # inline f32 literals render at >2 text bytes per weight byte;
        # an argument-passing module is untouched by model size
        assert module_bytes < weight_bytes / 2, (module_bytes,
                                                 weight_bytes)


class TestPagedParity:
    def test_prefill_decode_token_exact(self, model, prompt):
        outs = {}
        for flag in (True, False):
            parts = llama_paged_decode_factory(
                model, page_size=8, n_pool_pages=32, scan_layers=flag)
            outer, layers, pools, prefill, step, _ = parts
            pt = jnp.asarray(np.arange(8, dtype=np.int32).reshape(2, 4))
            lens = jnp.asarray([6, 6], jnp.int32)
            tok = jnp.asarray(
                np.pad(prompt, ((0, 0), (0, 2))))  # pad to page multiple
            nxt, pools = prefill(outer, layers, tok, pt, lens, pools)
            toks = [np.asarray(nxt)]
            for i in range(4):
                nxt, pools = step(outer, layers, nxt, pt, lens + i,
                                  pools)
                toks.append(np.asarray(nxt))
            outs[flag] = np.stack(toks)
        np.testing.assert_array_equal(outs[True], outs[False])
