// Native BERT tokenizer fast path (basic split + greedy wordpiece).
//
// ~ the reference ecosystem's faster_tokenizer C++ core: tokenization is
// host-side data-pipeline work that Python does one char at a time; this
// does the ASCII common case in one pass. Non-ASCII texts are flagged
// (out_lens[i] = -1) for the Python implementation, which owns unicode
// normalization/CJK splitting — the two paths are behavior-identical on
// the inputs the native one accepts (tests/test_strings.py parity test).
//
// API (ctypes, paddle_tpu/utils/native.py):
//   wp_new(blob, offsets, n)    vocab pieces, concatenated + offsets
//   wp_encode(handle, blob, offsets, n, unk, max_chars, lower,
//             out_ids, out_lens, max_out)
//   wp_free(handle)
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>

namespace {

struct Vocab {
    std::unordered_map<std::string, int32_t> map;
};

inline bool is_punct(unsigned char c) {
    return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
           (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

// greedy longest-match-first wordpiece; returns false on [UNK]
bool wordpiece(const Vocab& v, const std::string& word, int32_t unk_id,
               int32_t max_chars, int32_t* out, int32_t& n,
               int32_t max_out) {
    if ((int32_t)word.size() > max_chars) {
        if (n >= max_out) return false;
        out[n++] = unk_id;
        return true;
    }
    size_t start = 0;
    int32_t first = n;
    while (start < word.size()) {
        size_t end = word.size();
        int32_t id = -1;
        while (start < end) {
            std::string piece = (start > 0 ? "##" : "") +
                                word.substr(start, end - start);
            auto it = v.map.find(piece);
            if (it != v.map.end()) { id = it->second; break; }
            --end;
        }
        if (id < 0) {  // whole word -> UNK (BERT semantics)
            n = first;
            if (n >= max_out) return false;
            out[n++] = unk_id;
            return true;
        }
        if (n >= max_out) return false;
        out[n++] = id;
        start = end;
    }
    return true;
}

}  // namespace

extern "C" {

void* wp_new(const char* blob, const int32_t* offsets, int32_t n) {
    auto* v = new Vocab();
    v->map.reserve(n * 2);
    for (int32_t i = 0; i < n; ++i)
        v->map.emplace(std::string(blob + offsets[i],
                                   offsets[i + 1] - offsets[i]), i);
    return v;
}

void wp_free(void* h) { delete static_cast<Vocab*>(h); }

// Encodes n texts. out_ids is (n, max_out) int32 row-major; out_lens[i]
// is the id count, or -1 when the text needs the Python path (non-ASCII
// byte seen) or the row overflowed max_out.
void wp_encode(void* h, const char* blob, const int32_t* offsets,
               int32_t n, int32_t unk_id, int32_t max_chars,
               int32_t do_lower, int32_t* out_ids, int32_t* out_lens,
               int32_t max_out) {
    const Vocab& v = *static_cast<Vocab*>(h);
    for (int32_t i = 0; i < n; ++i) {
        const char* s = blob + offsets[i];
        int32_t len = offsets[i + 1] - offsets[i];
        int32_t* row = out_ids + (int64_t)i * max_out;
        int32_t cnt = 0;
        bool ok = true;
        std::string word;
        for (int32_t j = 0; j <= len && ok; ++j) {
            unsigned char c = j < len ? (unsigned char)s[j] : ' ';
            if (c >= 0x80) { ok = false; break; }  // Python path owns it
            // rare control chars (0x00-0x1f outside \t\n\v\f\r) differ
            // between str.isspace() and any simple C rule — punt them
            if (c < 0x20 && !(c >= '\t' && c <= '\r')) { ok = false;
                                                        break; }
            if (do_lower && c >= 'A' && c <= 'Z') c += 32;
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                c == '\f' || c == '\v' || is_punct(c)) {
                if (!word.empty()) {
                    ok = wordpiece(v, word, unk_id, max_chars, row, cnt,
                                   max_out);
                    word.clear();
                }
                if (ok && is_punct(c)) {
                    std::string p(1, (char)c);
                    ok = wordpiece(v, p, unk_id, max_chars, row, cnt,
                                   max_out);
                }
            } else {
                word.push_back((char)c);
            }
        }
        out_lens[i] = ok ? cnt : -1;
    }
}

}  // extern "C"
