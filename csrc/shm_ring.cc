// Shared-memory record ring for multiprocess DataLoader workers.
//
// ~ the reference's shared-memory LoDTensor transport between DataLoader
// worker processes and the trainer (python/paddle/fluid/dataloader/
// dataloader_iter.py:542 riding memory/allocation/mmap_allocator.h): worker
// processes serialize batches into a POSIX shm segment instead of piping
// bytes through multiprocessing queues.
//
// Layout: header { write_ticket, read_ticket, n_slots, slot_size } followed
// by n_slots slots of { seq, size, payload[slot_size] }. Vyukov-style
// bounded MPSC: producers atomically take a write ticket, wait for their
// slot to drain, memcpy, then publish by setting slot.seq = ticket + 1.
// The single consumer takes read tickets in order, so records arrive
// ticket-ordered even with racing producers.
#include <atomic>
#include <cstdint>
#include <cstring>
#include <cstdio>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct Header {
  std::atomic<uint64_t> write_ticket;
  std::atomic<uint64_t> read_ticket;
  uint64_t n_slots;
  uint64_t slot_size;
};

struct Slot {
  std::atomic<uint64_t> seq;  // published when seq == ticket + 1
  uint64_t size;
  // payload follows
};

struct Ring {
  Header* hdr;
  char* base;
  size_t total;
  int fd;
  bool owner;
  char name[256];
};

inline Slot* slot_at(Ring* r, uint64_t idx) {
  size_t stride = sizeof(Slot) + r->hdr->slot_size;
  return reinterpret_cast<Slot*>(r->base + sizeof(Header) + idx * stride);
}

inline void backoff(unsigned n) {
  struct timespec ts {0, n < 16 ? 1000L : 100000L};  // 1us then 100us
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, int64_t slot_size, int64_t n_slots) {
  slot_size = (slot_size + 7) & ~int64_t(7);  // keep Slot atomics aligned
  size_t total = sizeof(Header) +
                 (sizeof(Slot) + (size_t)slot_size) * (size_t)n_slots;
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = reinterpret_cast<Header*>(mem);
  r->base = reinterpret_cast<char*>(mem);
  r->total = total;
  r->fd = fd;
  r->owner = true;
  snprintf(r->name, sizeof(r->name), "%s", name);
  r->hdr->write_ticket.store(0);
  r->hdr->read_ticket.store(0);
  r->hdr->n_slots = (uint64_t)n_slots;
  r->hdr->slot_size = (uint64_t)slot_size;
  for (int64_t i = 0; i < n_slots; ++i) {
    slot_at(r, (uint64_t)i)->seq.store((uint64_t)i);  // "empty for turn 0"
  }
  return r;
}

void* shm_ring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = reinterpret_cast<Header*>(mem);
  r->base = reinterpret_cast<char*>(mem);
  r->total = (size_t)st.st_size;
  r->fd = fd;
  r->owner = false;
  snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

int64_t shm_ring_slot_size(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  return (int64_t)r->hdr->slot_size;
}

// Producer: claim a ticket, wait for the slot, copy, publish.
// Returns the ticket (>=0) or -1 if payload exceeds slot_size.
int64_t shm_ring_write(void* handle, const void* buf, int64_t n) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  if ((uint64_t)n > r->hdr->slot_size) return -1;
  uint64_t ticket = r->hdr->write_ticket.fetch_add(1);
  uint64_t ns = r->hdr->n_slots;
  Slot* s = slot_at(r, ticket % ns);
  // wait until the slot's previous occupant (ticket - n_slots) was consumed:
  // consumer sets seq = old_ticket + n_slots after reading
  for (unsigned spin = 0; s->seq.load(std::memory_order_acquire) != ticket;
       ++spin) {
    backoff(spin);
  }
  s->size = (uint64_t)n;
  memcpy(reinterpret_cast<char*>(s) + sizeof(Slot), buf, (size_t)n);
  s->seq.store(ticket + 1, std::memory_order_release);  // published
  return (int64_t)ticket;
}

// Consumer: read the next record in ticket order into buf (cap bytes).
// Returns bytes read (0 = legitimately empty record), -2 on timeout
// (timeout_us), -1 if cap too small.
int64_t shm_ring_read(void* handle, void* buf, int64_t cap,
                      int64_t timeout_us) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  uint64_t ticket = r->hdr->read_ticket.load();
  uint64_t ns = r->hdr->n_slots;
  Slot* s = slot_at(r, ticket % ns);
  int64_t waited = 0;
  for (unsigned spin = 0;
       s->seq.load(std::memory_order_acquire) != ticket + 1; ++spin) {
    if (timeout_us >= 0 && waited > timeout_us) return -2;
    backoff(spin);
    waited += spin < 16 ? 1 : 100;
  }
  int64_t n = (int64_t)s->size;
  if (n > cap) return -1;
  memcpy(buf, reinterpret_cast<char*>(s) + sizeof(Slot), (size_t)n);
  s->seq.store(ticket + ns, std::memory_order_release);  // slot drained
  r->hdr->read_ticket.store(ticket + 1);
  return n;
}

void shm_ring_close(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  munmap(r->base, r->total);
  close(r->fd);
  if (r->owner) shm_unlink(r->name);
  delete r;
}

}  // extern "C"
