// BatchLoader: threaded batch assembly for fixed-record datasets.
//
// Native data-path equivalent of the reference's C++ DataLoader machinery
// (the shared-memory LoDTensor transport of fluid/dataloader and the
// framework/data_feed.cc async readers): worker threads gather sample rows
// from a source buffer (user numpy array or mmap'ed file) into prefetched
// batch buffers on a lock-free-ish ring, fully outside the GIL.
//
// C ABI for ctypes:
//   bl_create(src_ptr, n_samples, sample_bytes, batch_size, n_threads,
//             queue_cap) -> handle
//   bl_submit(handle, indices_ptr, count)  // enqueue one batch's indices
//   bl_next(handle, out_ptr)               // blocking; copies batch out
//   bl_destroy(handle)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Batch {
  int64_t seq;
  std::vector<char> data;
};

struct Loader {
  const char* src;
  int64_t n_samples;
  int64_t sample_bytes;
  int64_t batch_size;
  size_t queue_cap;

  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::deque<std::pair<int64_t, std::vector<int64_t>>> work;  // seq, indices
  std::deque<Batch> done;
  int64_t next_submit = 0;
  int64_t next_emit = 0;
  bool stop = false;
  std::vector<std::thread> threads;

  void worker() {
    for (;;) {
      std::pair<int64_t, std::vector<int64_t>> job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stop || !work.empty(); });
        if (stop) return;
        job = std::move(work.front());
        work.pop_front();
      }
      Batch b;
      b.seq = job.first;
      b.data.resize(static_cast<size_t>(job.second.size()) *
                    static_cast<size_t>(sample_bytes));
      char* dst = b.data.data();
      for (int64_t idx : job.second) {
        std::memcpy(dst, src + idx * sample_bytes,
                    static_cast<size_t>(sample_bytes));
        dst += sample_bytes;
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        done.push_back(std::move(b));
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* bl_create(const char* src, int64_t n_samples, int64_t sample_bytes,
                int64_t batch_size, int n_threads, int queue_cap) {
  Loader* l = new Loader();
  l->src = src;
  l->n_samples = n_samples;
  l->sample_bytes = sample_bytes;
  l->batch_size = batch_size;
  l->queue_cap = static_cast<size_t>(queue_cap);
  for (int i = 0; i < n_threads; ++i)
    l->threads.emplace_back([l] { l->worker(); });
  return l;
}

int64_t bl_submit(void* handle, const int64_t* indices, int64_t count) {
  Loader* l = static_cast<Loader*>(handle);
  std::vector<int64_t> idx(indices, indices + count);
  int64_t seq;
  {
    std::unique_lock<std::mutex> lk(l->mu);
    seq = l->next_submit++;
    l->work.emplace_back(seq, std::move(idx));
  }
  l->cv_work.notify_one();
  return seq;
}

// blocking: copies the NEXT in-order batch into out; returns its byte size
int64_t bl_next(void* handle, char* out) {
  Loader* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  for (;;) {
    for (auto it = l->done.begin(); it != l->done.end(); ++it) {
      if (it->seq == l->next_emit) {
        int64_t n = static_cast<int64_t>(it->data.size());
        std::memcpy(out, it->data.data(), it->data.size());
        l->done.erase(it);
        l->next_emit++;
        return n;
      }
    }
    l->cv_done.wait(lk);
  }
}

void bl_destroy(void* handle) {
  Loader* l = static_cast<Loader*>(handle);
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->stop = true;
  }
  l->cv_work.notify_all();
  for (auto& t : l->threads) t.join();
  delete l;
}

}  // extern "C"
