// TCPStore: rendezvous key-value store.
//
// TPU-native equivalent of the reference's C++ TCPStore
// (paddle/fluid/distributed/store/tcp_store.h:91, tcp_utils.cc): a
// master-hosted KV with blocking wait/add used for process-group bootstrap.
// Here it backs paddle_tpu.distributed.store (jax.distributed's coordinator
// handles collective init; this store serves the script-level barrier /
// key-exchange API the reference exposes to users).
//
// Protocol (length-prefixed):
//   request : u8 op | u32 klen | key | u32 vlen | value
//   ops     : 0=SET 1=GET 2=ADD(i64 delta in value) 3=WAIT 4=DELETE
//   response: u32 vlen | value   (GET/ADD/WAIT; SET/DELETE reply vlen=0)
//
// Exposed as a C ABI for ctypes; server runs detached threads per client.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_value(int fd, const std::string& v) {
  uint32_t len = static_cast<uint32_t>(v.size());
  if (!write_full(fd, &len, 4)) return false;
  return v.empty() || write_full(fd, v.data(), v.size());
}

void serve_client(Store* store, int fd) {
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_full(fd, &key[0], klen)) break;
    if (!read_full(fd, &vlen, 4)) break;
    std::string value(vlen, '\0');
    if (vlen && !read_full(fd, &value[0], vlen)) break;

    bool ok = true;
    switch (op) {
      case 0: {  // SET
        {
          std::lock_guard<std::mutex> lk(store->mu);
          store->data[key] = value;
        }
        store->cv.notify_all();
        ok = send_value(fd, "");
        break;
      }
      case 1: {  // GET (non-blocking; missing -> empty)
        std::string out;
        {
          std::lock_guard<std::mutex> lk(store->mu);
          auto it = store->data.find(key);
          if (it != store->data.end()) out = it->second;
        }
        ok = send_value(fd, out);
        break;
      }
      case 2: {  // ADD
        int64_t delta = 0;
        if (value.size() == 8) std::memcpy(&delta, value.data(), 8);
        int64_t result;
        {
          std::lock_guard<std::mutex> lk(store->mu);
          int64_t cur = 0;
          auto it = store->data.find(key);
          if (it != store->data.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          result = cur + delta;
          std::string stored(8, '\0');
          std::memcpy(&stored[0], &result, 8);
          store->data[key] = stored;
        }
        store->cv.notify_all();
        std::string out(8, '\0');
        std::memcpy(&out[0], &result, 8);
        ok = send_value(fd, out);
        break;
      }
      case 3: {  // WAIT (block until key exists)
        std::string out;
        {
          std::unique_lock<std::mutex> lk(store->mu);
          store->cv.wait(lk, [&] {
            return store->data.count(key) > 0;
          });
          out = store->data[key];
        }
        ok = send_value(fd, out);
        break;
      }
      case 4: {  // DELETE
        {
          std::lock_guard<std::mutex> lk(store->mu);
          store->data.erase(key);
        }
        ok = send_value(fd, "");
        break;
      }
      default:
        ok = false;
    }
    if (!ok) break;
  }
  ::close(fd);
}

void accept_loop(Store* store, int listen_fd) {
  for (;;) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve_client, store, fd).detach();
  }
}

int connect_to(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

bool roundtrip(int fd, uint8_t op, const std::string& key,
               const std::string& value, std::string* out) {
  uint32_t klen = static_cast<uint32_t>(key.size());
  uint32_t vlen = static_cast<uint32_t>(value.size());
  if (!write_full(fd, &op, 1) || !write_full(fd, &klen, 4) ||
      (klen && !write_full(fd, key.data(), klen)) ||
      !write_full(fd, &vlen, 4) ||
      (vlen && !write_full(fd, value.data(), vlen)))
    return false;
  uint32_t rlen;
  if (!read_full(fd, &rlen, 4)) return false;
  out->assign(rlen, '\0');
  return rlen == 0 || read_full(fd, &(*out)[0], rlen);
}

}  // namespace

extern "C" {

// ---- server ----
void* tcpstore_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  Store* store = new Store();
  std::thread(accept_loop, store, fd).detach();
  return store;
}

// ---- client ----
int tcpstore_connect(const char* host, int port) {
  return connect_to(host, port);
}

int tcpstore_set(int fd, const char* key, const char* value, int vlen) {
  std::string out;
  return roundtrip(fd, 0, key, std::string(value, vlen), &out) ? 0 : -1;
}

// returns length, copies up to cap bytes into buf; -1 on error
int tcpstore_get(int fd, const char* key, char* buf, int cap) {
  std::string out;
  if (!roundtrip(fd, 1, key, "", &out)) return -1;
  int n = static_cast<int>(out.size());
  std::memcpy(buf, out.data(), std::min(n, cap));
  return n;
}

int64_t tcpstore_add(int fd, const char* key, int64_t delta) {
  std::string v(8, '\0');
  std::memcpy(&v[0], &delta, 8);
  std::string out;
  if (!roundtrip(fd, 2, key, v, &out) || out.size() != 8) return INT64_MIN;
  int64_t result;
  std::memcpy(&result, out.data(), 8);
  return result;
}

int tcpstore_wait(int fd, const char* key, char* buf, int cap) {
  std::string out;
  if (!roundtrip(fd, 3, key, "", &out)) return -1;
  int n = static_cast<int>(out.size());
  std::memcpy(buf, out.data(), std::min(n, cap));
  return n;
}

int tcpstore_delete(int fd, const char* key) {
  std::string out;
  return roundtrip(fd, 4, key, "", &out) ? 0 : -1;
}

void tcpstore_close(int fd) { ::close(fd); }

}  // extern "C"
