// Native profiler span collector.
//
// ~ the reference's HostTracer ring (paddle/fluid/platform/profiler/
// host_tracer.h:46 consuming RecordEvent spans, event collection in
// host_event_recorder.h): the per-op instrumentation path runs on every
// eager dispatch, so span recording must not contend or allocate.
// This is a fixed-capacity ring of POD records with an interned name table;
// writers take an atomic slot (overwrite-oldest), the only lock guards the
// cold name-intern path.
//
// C ABI for ctypes (paddle_tpu/profiler binds with python fallback parity).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct SpanRecord {
  int32_t name_id;
  int32_t pad;
  double t0;       // seconds
  double dur;      // seconds
  uint64_t tid;
};

struct Collector {
  std::vector<SpanRecord> ring;
  std::atomic<uint64_t> next{0};
  std::mutex intern_mu;
  std::unordered_map<std::string, int32_t> ids;
  std::vector<std::string> names;

  explicit Collector(size_t cap) : ring(cap) {}
};

}  // namespace

extern "C" {

void* spans_create(uint64_t capacity) {
  if (capacity == 0) capacity = 1 << 16;
  return new Collector(static_cast<size_t>(capacity));
}

void spans_destroy(void* h) { delete static_cast<Collector*>(h); }

int32_t spans_intern(void* h, const char* name) {
  auto* c = static_cast<Collector*>(h);
  std::lock_guard<std::mutex> g(c->intern_mu);
  auto it = c->ids.find(name);
  if (it != c->ids.end()) return it->second;
  int32_t id = static_cast<int32_t>(c->names.size());
  c->names.emplace_back(name);
  c->ids.emplace(name, id);
  return id;
}

void spans_add(void* h, int32_t name_id, double t0, double dur,
               uint64_t tid) {
  auto* c = static_cast<Collector*>(h);
  uint64_t slot = c->next.fetch_add(1, std::memory_order_relaxed);
  SpanRecord& r = c->ring[slot % c->ring.size()];
  r.name_id = name_id;
  r.t0 = t0;
  r.dur = dur;
  r.tid = tid;
}

uint64_t spans_count(void* h) {
  auto* c = static_cast<Collector*>(h);
  uint64_t n = c->next.load(std::memory_order_relaxed);
  uint64_t cap = c->ring.size();
  return n < cap ? n : cap;
}

uint64_t spans_total(void* h) {
  return static_cast<Collector*>(h)->next.load(std::memory_order_relaxed);
}

// Copy up to max_n oldest-to-newest records into parallel output arrays.
// Returns number copied.
uint64_t spans_dump(void* h, int32_t* name_ids, double* t0s, double* durs,
                    uint64_t* tids, uint64_t max_n) {
  auto* c = static_cast<Collector*>(h);
  uint64_t total = c->next.load(std::memory_order_relaxed);
  uint64_t cap = c->ring.size();
  uint64_t n = total < cap ? total : cap;
  if (n > max_n) n = max_n;
  uint64_t start = total < cap ? 0 : total % cap;  // oldest slot
  for (uint64_t i = 0; i < n; ++i) {
    const SpanRecord& r = c->ring[(start + i) % cap];
    name_ids[i] = r.name_id;
    t0s[i] = r.t0;
    durs[i] = r.dur;
    tids[i] = r.tid;
  }
  return n;
}

// Name for an interned id; returns bytes copied (0 if unknown).
uint64_t spans_name(void* h, int32_t id, char* out, uint64_t out_len) {
  auto* c = static_cast<Collector*>(h);
  std::lock_guard<std::mutex> g(c->intern_mu);
  if (id < 0 || static_cast<size_t>(id) >= c->names.size()) return 0;
  if (out_len == 0) return 0;  // out_len-1 would wrap to UINT64_MAX below
  const std::string& s = c->names[id];
  uint64_t n = s.size() < out_len - 1 ? s.size() : out_len - 1;
  std::memcpy(out, s.data(), n);
  out[n] = '\0';
  return n;
}

void spans_reset(void* h) {
  auto* c = static_cast<Collector*>(h);
  c->next.store(0, std::memory_order_relaxed);
}

}  // extern "C"
