"""Functional Llama: scan-over-layers + pipeline-parallel training.

The nn.Layer Llama (llama.py) is the eager/API surface; this module is the
scaled execution form:
  * layer params STACKED along a leading axis; the decoder stack runs as
    ``lax.scan`` over layer params — one compiled layer body regardless of
    depth (fast compiles, natural remat granularity), and the stacking is
    exactly what pipeline parallelism needs.
  * ``llama_pp_train_step_factory``: dp x pp training. Decoder layers are
    split into `pipe` stages (leading axis sharded over the 'pipe' mesh
    axis); microbatches flow through parallel.pipeline_apply (shard_map +
    ppermute), embedding/norm/lm-head run replicated outside the rotation.
    This is the compiled replacement for the reference's 1F1B runtime
    (SURVEY.md §2.2 pipeline rows) composed with data parallelism.
"""
from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import LlamaConfig, LlamaForCausalLM, apply_rotary

LAYER_KEYS = [
    "input_layernorm.weight",
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "post_attention_layernorm.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
]


def stack_layers(per_layer: list) -> Dict[str, jax.Array]:
    """List of L per-layer param dicts -> one dict of (L, ...) stacked
    leaves. THE stacking convention: train (scan-over-layers forward),
    pipeline stage splitting, and the decode factories all consume this
    layout, so a weight tree round-trips between them with no reshapes."""
    keys = per_layer[0].keys()
    return {k: jnp.stack([p[k] for p in per_layer]) for k in keys}


def unstack_layers(stacked: Dict[str, jax.Array]) -> list:
    """Inverse of stack_layers: (L, ...) leaves -> list of L dicts."""
    L = next(iter(stacked.values())).shape[0]
    return [{k: v[i] for k, v in stacked.items()} for i in range(L)]


def split_params(model: LlamaForCausalLM):
    """model state_dict -> (outer_params, stacked_layer_params)."""
    sd = {k: v._value for k, v in model.state_dict().items()}
    L = model.config.num_hidden_layers
    per_layer = [{key: sd.pop(f"model.layers.{i}.{key}")
                  for key in LAYER_KEYS} for i in range(L)]
    return sd, stack_layers(per_layer)


def merge_params(model: LlamaForCausalLM, outer, layers):
    sd = dict(outer)
    for i, lp in enumerate(unstack_layers(layers)):
        for key, leaf in lp.items():
            sd[f"model.layers.{i}.{key}"] = leaf
    model.load_tree(sd)


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    return y.astype(x.dtype) * w


_FORCE_FLASH_FOR_TESTS = False  # CPU interpret-mode flash in the factories


def layer_forward(cfg: LlamaConfig, p: Dict[str, jax.Array], x):
    """One decoder layer over its param dict (pure)."""
    B, S, H = x.shape
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = H // nh
    h = _rms(x, p["input_layernorm.weight"], cfg.rms_norm_eps)
    q = (h @ p["self_attn.q_proj.weight"]).reshape(B, S, nh, hd)
    k = (h @ p["self_attn.k_proj.weight"]).reshape(B, S, nkv, hd)
    v = (h @ p["self_attn.v_proj.weight"]).reshape(B, S, nkv, hd)
    pos = jnp.arange(S)
    q = apply_rotary(q, pos, cfg.rope_theta)
    k = apply_rotary(k, pos, cfg.rope_theta)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)  # (B, nkv, S, hd) — true kv head count
    vt = jnp.swapaxes(v, 1, 2)
    use_flash = (S >= 256 and S % 128 == 0 and hd in (64, 128, 256)
                 and qt.dtype in (jnp.float32, jnp.bfloat16)
                 and (jax.default_backend() != "cpu"
                      or _FORCE_FLASH_FOR_TESTS))
    if use_flash:
        # GQA configs keep K/V at nkv heads (grouped kernel — no repeat
        # blowup through HBM)
        if nh != nkv:
            from ...ops.pallas.flash_attention_gqa import (
                grouped_flash_attention as _fa)
        else:
            from ...ops.pallas.flash_attention import flash_attention as _fa
        # GSPMD can't partition a Pallas call: when this stage body runs
        # with a >1 AUTO 'model' axis (the 4D factory's partial-manual
        # pipeline), the shared wrapper nests a shard_map so heads go
        # manual instead of all-gathering Q/K/V per microbatch
        from ...parallel.pallas_sharding import shard_map_attention
        ctx = shard_map_attention(lambda a, b, c: _fa(a, b, c, True),
                                  qt, kt, vt)
    else:
        if nh != nkv:
            kt = jnp.repeat(kt, nh // nkv, axis=1)
            vt = jnp.repeat(vt, nh // nkv, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / math.sqrt(hd)
        causal = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(causal, s, jnp.finfo(s.dtype).min)
        probs = jax.nn.softmax(s.astype(jnp.float32), -1).astype(qt.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    attn = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H) \
        @ p["self_attn.o_proj.weight"]
    x = x + attn
    h2 = _rms(x, p["post_attention_layernorm.weight"], cfg.rms_norm_eps)
    mlp = (jax.nn.silu(h2 @ p["mlp.gate_proj.weight"])
           * (h2 @ p["mlp.up_proj.weight"])) @ p["mlp.down_proj.weight"]
    return x + mlp


def forward(cfg: LlamaConfig, outer, layers, tokens, remat=True):
    """Full causal-LM forward with lax.scan over stacked layers."""
    x = jnp.take(outer["model.embed_tokens.weight"], tokens, axis=0)

    body = (lambda carry, lp: (layer_forward(cfg, lp, carry), None))
    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, layers)
    x = _rms(x, outer["model.norm.weight"], cfg.rms_norm_eps)
    head = outer.get("lm_head.weight")
    if head is None:
        return x @ outer["model.embed_tokens.weight"].T
    return x @ head


def _ce(logits, labels):
    """Causal-LM CE: Pallas fused softmax-xent on TPU (no (N,V) softmax
    HBM round-trip), dense log_softmax on CPU."""
    if jax.default_backend() != "cpu":
        from ...ops.pallas.fused_ce import causal_lm_loss
        return causal_lm_loss(logits, labels)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    return jnp.mean(-jnp.take_along_axis(logp, labels[..., None], -1)[..., 0])


def loss_fn(cfg, outer, layers, tokens, labels, remat=True):
    logits = forward(cfg, outer, layers, tokens, remat)
    return _ce(logits, labels)


def llama_pp_train_step_factory(model: LlamaForCausalLM, mesh: Mesh,
                                n_microbatches: int = 2,
                                learning_rate=1e-4, weight_decay=0.01,
                                beta1=0.9, beta2=0.95, eps=1e-8,
                                remat: bool = True, n_virtual: int = 1):
    """dp x pp compiled training step.

    mesh axes: 'pipe' (required) and optionally 'data'. Decoder layers are
    evenly split over stages; stage leaf shape (n_stages, L/stage, ...).
    n_virtual > 1 switches to the breadth-first interleaved schedule
    (pipeline_apply_interleaved): layers lay out as (V, P, L/(P*V), ...)
    with round-robin stage placement, shrinking the pipeline bubble by V.
    Returns (params, opt_state, step_fn).
    """
    from ...parallel.pipeline import (pipeline_apply,
                                      pipeline_apply_interleaved)

    cfg = model.config
    n_stages = mesh.shape["pipe"]
    data_axis = "data" if "data" in mesh.axis_names else None
    L = cfg.num_hidden_layers
    V = n_virtual
    assert L % (n_stages * V) == 0, (L, n_stages, V)
    per = L // (n_stages * V)

    outer, layers = split_params(model)
    if V > 1:
        # (L, ...) -> (V, P, per, ...): [v, d] holds global stage v*P + d,
        # i.e. decoder layers (v*P + d)*per ... +per
        layers = jax.tree.map(
            lambda a: jnp.array(a, copy=True).reshape(
                (V, n_stages, per) + a.shape[1:]), layers)
        pipe_spec = P(None, "pipe")
    else:
        # reshape stacked layers (L, ...) -> (n_stages, per, ...)
        layers = jax.tree.map(
            lambda a: jnp.array(a, copy=True).reshape(
                (n_stages, per) + a.shape[1:]), layers)
        pipe_spec = P("pipe")
    outer = {k: jnp.array(v, copy=True) for k, v in outer.items()}

    rep = NamedSharding(mesh, P())
    pipe_sh = {k: NamedSharding(mesh, pipe_spec)
               for k in layers}
    outer_sh = {k: rep for k in outer}
    outer = {k: jax.device_put(v, rep) for k, v in outer.items()}
    layers = {k: jax.device_put(v, pipe_sh[k]) for k, v in layers.items()}

    params = {"outer": outer, "layers": layers}
    shardings = {"outer": outer_sh, "layers": pipe_sh}
    moments_sh = shardings

    def zeros_like_tree(tree, sh):
        return {k: jax.device_put(jnp.zeros(v.shape, jnp.float32), sh[k])
                for k, v in tree.items()}

    opt_state = {
        # committed to the mesh: an uncommitted scalar aval mismatches
        # the jit output's and recompiles the step (see make_adamw_state)
        "step": jax.device_put(jnp.zeros((), jnp.int32), rep),
        "m": {"outer": zeros_like_tree(outer, outer_sh),
              "layers": zeros_like_tree(layers, pipe_sh)},
        "v": {"outer": zeros_like_tree(outer, outer_sh),
              "layers": zeros_like_tree(layers, pipe_sh)},
    }

    def stage_fn(stage_params, x):
        body = lambda carry, lp: (layer_forward(cfg, lp, carry), None)
        x, _ = jax.lax.scan(body, x, stage_params)
        return x

    def pipe_loss(params, tokens, labels):
        emb = jnp.take(params["outer"]["model.embed_tokens.weight"], tokens,
                       axis=0)
        if V > 1:
            h = pipeline_apply_interleaved(
                stage_fn, params["layers"], emb, mesh, n_microbatches,
                n_virtual=V, remat=remat, data_axis=data_axis,
                params_layout="vp")
        else:
            h = pipeline_apply(stage_fn, params["layers"], emb, mesh,
                               n_microbatches, remat=remat,
                               data_axis=data_axis)
        h = _rms(h, params["outer"]["model.norm.weight"], cfg.rms_norm_eps)
        head = params["outer"].get("lm_head.weight")
        logits = (h @ (head if head is not None
                       else params["outer"]["model.embed_tokens.weight"].T))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        return jnp.mean(
            -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0])

    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(pipe_loss)(params, tokens, labels)
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            m2 = beta1 * m + (1 - beta1) * g
            v2 = beta2 * v + (1 - beta2) * jnp.square(g)
            mhat = m2 / (1 - beta1 ** t)
            vhat = v2 / (1 - beta2 ** t)
            delta = mhat / (jnp.sqrt(vhat) + eps) \
                + weight_decay * p.astype(jnp.float32)
            return ((p.astype(jnp.float32)
                     - learning_rate * delta).astype(p.dtype), m2, v2)

        new_p = {"outer": {}, "layers": {}}
        new_m = {"outer": {}, "layers": {}}
        new_v = {"outer": {}, "layers": {}}
        for grp in ("outer", "layers"):
            for k in params[grp]:
                new_p[grp][k], new_m[grp][k], new_v[grp][k] = upd(
                    params[grp][k], grads[grp][k],
                    opt_state["m"][grp][k], opt_state["v"][grp][k])
        return new_p, {"step": step, "m": new_m, "v": new_v}, loss

    batch_sh = NamedSharding(mesh, P(data_axis) if data_axis else P())
    jitted = jax.jit(
        train_step,
        in_shardings=({"outer": outer_sh, "layers": pipe_sh},
                      {"step": rep,
                       "m": {"outer": outer_sh, "layers": pipe_sh},
                       "v": {"outer": outer_sh, "layers": pipe_sh}},
                      batch_sh, batch_sh),
        donate_argnums=(0, 1))
    return params, opt_state, jitted


# ---------------------------------------------------------------------------
# Full 4D composition: data x sharding x model x pipe in ONE program
# ---------------------------------------------------------------------------

# TP layout of the stacked layer leaves (n_stages, per_stage, in, out):
# column-parallel projections shard the output dim over 'model',
# row-parallel shard the input dim (~ mp_layers.py ColumnParallelLinear:97 /
# RowParallelLinear:170 expressed as GSPMD specs)
_COL_KEYS = {"self_attn.q_proj.weight", "self_attn.k_proj.weight",
             "self_attn.v_proj.weight", "mlp.gate_proj.weight",
             "mlp.up_proj.weight"}
_ROW_KEYS = {"self_attn.o_proj.weight", "mlp.down_proj.weight"}


def llama_4d_train_step_factory(model: LlamaForCausalLM, mesh: Mesh,
                                n_microbatches: int = 2,
                                learning_rate=1e-4, weight_decay=0.01,
                                beta1=0.9, beta2=0.95, eps=1e-8,
                                remat: bool = True, n_virtual: int = 1):
    """ONE jitted train step over data x sharding x model x pipe.

    ~ the reference's 4D HybridCommunicateGroup axes
    (fleet/base/topology.py:52 ["data","pipe","sharding","model"]) — but
    composed by GSPMD in a single XLA program rather than four comm-group
    runtimes: 'pipe' rotates stages via ppermute inside a partial-manual
    shard_map, 'model' partitions the stage matmuls (TP), 'data' shards the
    microbatch, and 'sharding' holds the ZeRO-sharded adamw moments.
    Mesh axes absent (or size 1) degrade gracefully.
    """
    cfg = model.config
    # absent axes degrade to size 1 (the docstring contract): a planner
    # mesh may carry only the axes its plan actually uses
    n_stages = mesh.shape.get("pipe", 1)
    have = {a for a in mesh.axis_names if mesh.shape[a] > 1}
    data_axis = "data" if "data" in mesh.axis_names else None
    mdl = "model" if "model" in have else None
    L = cfg.num_hidden_layers
    V = n_virtual
    assert L % (n_stages * V) == 0, (L, n_stages, V)
    per = L // (n_stages * V)

    outer, layers = split_params(model)
    pipe_name = "pipe" if "pipe" in mesh.axis_names else None
    if pipe_name is None and n_microbatches > 1:
        # microbatching is a pipeline concept: without a pipe axis the
        # batch runs in one shot (use gradient_merge for accumulation),
        # so peak activation memory is NOT bounded by n_microbatches
        import warnings
        warnings.warn(
            "llama_4d_train_step_factory: mesh has no 'pipe' axis — "
            f"n_microbatches={n_microbatches} is ignored (full-batch "
            "step)", stacklevel=2)
    if V > 1:
        # (L, ...) -> (V, P, per, ...): [v, d] = global stage v*P + d
        # (breadth-first interleaved placement)
        layers = jax.tree.map(
            lambda a: jnp.array(a, copy=True).reshape(
                (V, n_stages, per) + a.shape[1:]), layers)
        pipe_prefix = [None, pipe_name]
    else:
        layers = jax.tree.map(
            lambda a: jnp.array(a, copy=True).reshape(
                (n_stages, per) + a.shape[1:]), layers)
        pipe_prefix = [pipe_name]
    outer = {k: jnp.array(v, copy=True) for k, v in outer.items()}

    def layer_spec(key, shape):
        spec = list(pipe_prefix) + [None] * (len(shape) - len(pipe_prefix))
        if mdl and key in _COL_KEYS and shape[-1] % mesh.shape[mdl] == 0:
            spec[-1] = mdl
        elif mdl and key in _ROW_KEYS and shape[-2] % mesh.shape[mdl] == 0:
            spec[-2] = mdl
        return P(*spec)

    def outer_spec(key, shape):
        if mdl and key == "model.embed_tokens.weight" \
                and shape[0] % mesh.shape[mdl] == 0:
            return P(mdl, None)   # vocab-parallel (~ VocabParallelEmbedding)
        if mdl and key == "lm_head.weight" \
                and shape[-1] % mesh.shape[mdl] == 0:
            return P(None, mdl)
        return P()

    def zero_spec(base: P, shape):
        """Moment layout: param spec + 'sharding' on the largest free,
        divisible dim (ZeRO over the 'sharding' axis)."""
        spec = list(base) + [None] * (len(shape) - len(base))
        if "sharding" in have:
            n = mesh.shape["sharding"]
            for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
                if spec[i] is None and shape[i] % n == 0 and shape[i] >= n:
                    spec[i] = "sharding"
                    break
        return P(*spec)

    layer_sh = {k: NamedSharding(mesh, layer_spec(k, v.shape))
                for k, v in layers.items()}
    outer_sh = {k: NamedSharding(mesh, outer_spec(k, v.shape))
                for k, v in outer.items()}
    layer_msh = {k: NamedSharding(mesh, zero_spec(layer_sh[k].spec, v.shape))
                 for k, v in layers.items()}
    outer_msh = {k: NamedSharding(mesh, zero_spec(outer_sh[k].spec, v.shape))
                 for k, v in outer.items()}

    outer = {k: jax.device_put(v, outer_sh[k]) for k, v in outer.items()}
    layers = {k: jax.device_put(v, layer_sh[k]) for k, v in layers.items()}
    params = {"outer": outer, "layers": layers}

    def zeros_tree(tree, sh):
        return {k: jax.device_put(jnp.zeros(v.shape, jnp.float32), sh[k])
                for k, v in tree.items()}

    rep = NamedSharding(mesh, P())
    opt_state = {
        "step": jax.device_put(jnp.zeros((), jnp.int32), rep),
        "m": {"outer": zeros_tree(outer, outer_msh),
              "layers": zeros_tree(layers, layer_msh)},
        "v": {"outer": zeros_tree(outer, outer_msh),
              "layers": zeros_tree(layers, layer_msh)},
    }

    def stage_fn(stage_params, x):
        body = lambda carry, lp: (layer_forward(cfg, lp, carry), None)
        x, _ = jax.lax.scan(body, x, stage_params)
        return x

    auto = {a for a in ("model", "sharding") if a in mesh.axis_names}

    def pipe_loss(params, tokens, labels):
        emb = jnp.take(params["outer"]["model.embed_tokens.weight"], tokens,
                       axis=0)
        from ...parallel.pipeline import (pipeline_apply,
                                          pipeline_apply_interleaved)
        if pipe_name is None:
            # no pipe axis on the planner's mesh: run the single stage
            # in place (GSPMD still applies data/model/sharding layouts);
            # remat must survive the degradation — the pipe branches get
            # it inside pipeline_apply. Microbatching is a pipeline
            # concept: without a pipe axis the batch runs in one shot
            # (use gradient_merge for accumulation), so warn when the
            # caller asked for it.
            assert V == 1, "virtual stages need a 'pipe' mesh axis"
            stage0 = jax.tree.map(lambda a: a[0], params["layers"])
            fn = jax.checkpoint(stage_fn) if remat else stage_fn
            h = fn(stage0, emb)
        elif V > 1:
            h = pipeline_apply_interleaved(
                stage_fn, params["layers"], emb, mesh, n_microbatches,
                n_virtual=V, remat=remat, data_axis=data_axis,
                auto_axes=auto, params_layout="vp")
        else:
            h = pipeline_apply(stage_fn, params["layers"], emb, mesh,
                               n_microbatches, remat=remat,
                               data_axis=data_axis, auto_axes=auto)
        h = _rms(h, params["outer"]["model.norm.weight"], cfg.rms_norm_eps)
        head = params["outer"].get("lm_head.weight")
        logits = (h @ (head if head is not None
                       else params["outer"]["model.embed_tokens.weight"].T))
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        return jnp.mean(
            -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0])

    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(pipe_loss)(params, tokens, labels)
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            m2 = beta1 * m + (1 - beta1) * g
            v2 = beta2 * v + (1 - beta2) * jnp.square(g)
            mhat = m2 / (1 - beta1 ** t)
            vhat = v2 / (1 - beta2 ** t)
            delta = mhat / (jnp.sqrt(vhat) + eps) \
                + weight_decay * p.astype(jnp.float32)
            return ((p.astype(jnp.float32)
                     - learning_rate * delta).astype(p.dtype), m2, v2)

        new_p = {"outer": {}, "layers": {}}
        new_m = {"outer": {}, "layers": {}}
        new_v = {"outer": {}, "layers": {}}
        for grp in ("outer", "layers"):
            for k in params[grp]:
                new_p[grp][k], new_m[grp][k], new_v[grp][k] = upd(
                    params[grp][k], grads[grp][k],
                    opt_state["m"][grp][k], opt_state["v"][grp][k])
        return new_p, {"step": step, "m": new_m, "v": new_v}, loss

    batch_sh = NamedSharding(mesh, P(data_axis) if data_axis else P())
    param_sh = {"outer": outer_sh, "layers": layer_sh}
    mom_sh = {"outer": outer_msh, "layers": layer_msh}
    jitted = jax.jit(
        train_step,
        in_shardings=(param_sh,
                      {"step": rep, "m": mom_sh, "v": mom_sh},
                      batch_sh, batch_sh),
        out_shardings=(param_sh,
                       {"step": rep, "m": mom_sh, "v": mom_sh},
                       rep),
        donate_argnums=(0, 1))
    return params, opt_state, jitted
