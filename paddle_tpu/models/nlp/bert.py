"""BERT — BASELINE config 3 capability slot (PaddleNLP bert-base pretrain).

Encoder-only transformer on the nn.TransformerEncoder stack; MLM+NSP heads.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from ... import nn
from ...nn import functional as F


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=512, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=128, hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle
        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int64")
        emb = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    """~ PaddleNLP BertModel capability."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob)
        self.encoder = nn.TransformerEncoder(layer, config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # (B, S) 1/0 -> additive (B, 1, 1, S)
            from ...ops.dispatch import apply_op
            import jax.numpy as jnp

            def to_additive(m):
                return ((1.0 - m.astype(jnp.float32))
                        * jnp.finfo(jnp.float32).min)[:, None, None, :]
            attention_mask = apply_op("bert_mask", to_additive,
                                      attention_mask, nondiff=True)
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size,
                                     config.layer_norm_eps)
        self.nsp_head = nn.Linear(config.hidden_size, 2)
        self.act = getattr(F, config.hidden_act)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(self.act(self.mlm_transform(seq)))
        from ...ops.linalg import matmul
        mlm_logits = matmul(h, self.bert.embeddings.word_embeddings.weight,
                            transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    def loss(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels,
             ignore_index=-100):
        mlm = F.cross_entropy(mlm_logits, mlm_labels,
                              ignore_index=ignore_index)
        nsp = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_pretrain_step_factory(model: BertForPretraining, mesh,
                               learning_rate=1e-4, weight_decay=0.01,
                               beta1=0.9, beta2=0.999, eps=1e-8,
                               remat=False):
    """(params, opt_state, step) for compiled BERT pretraining
    (BASELINE.md config 3: PaddleNLP BERT-base pretraining, Fleet DP).

    Same pjit pattern as llama_train_step_factory (llama.py): params per
    sharding annotation, adamw moments optionally ZeRO-sharded over
    'sharding', batch over 'data'. Loss = masked-LM CE (ignore_index -100)
    + NSP CE.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...autograd import no_grad
    from ...core.tensor import Tensor
    from .llama import param_shardings

    from .train_utils import (adamw_state_shardings, adamw_update,
                              make_adamw_state)
    shardings = param_shardings(model, mesh)
    params = {k: jax.device_put(jnp.array(v._value, copy=True), shardings[k])
              for k, v in model.state_dict().items()}
    opt_state = make_adamw_state(mesh, shardings, params)
    data_sh = NamedSharding(
        mesh, P("data" if "data" in mesh.axis_names else None))

    def forward_loss(params, input_ids, type_ids, mlm_labels, nsp_labels):
        saved = model.tree_flatten_params()
        model.load_tree(params)
        try:
            with no_grad():
                mlm_logits, nsp_logits = model(Tensor(input_ids),
                                               Tensor(type_ids))
                mlm_logits = mlm_logits._value
                nsp_logits = nsp_logits._value
        finally:
            model.load_tree(saved)
        V = mlm_logits.shape[-1]
        flat = mlm_logits.reshape(-1, V).astype(jnp.float32)
        lbl = mlm_labels.reshape(-1)
        valid = lbl != -100
        logp = jax.nn.log_softmax(flat, -1)
        nll = -jnp.take_along_axis(
            logp, jnp.where(valid, lbl, 0)[:, None], -1)[:, 0]
        mlm_loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)
        nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), -1)
        nsp_loss = -jnp.mean(
            jnp.take_along_axis(nsp_logp, nsp_labels[:, None], -1)[:, 0])
        return mlm_loss + nsp_loss

    loss_fn = jax.checkpoint(forward_loss) if remat else forward_loss

    def train_step(params, opt_state, input_ids, type_ids, mlm_labels,
                   nsp_labels):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, input_ids, type_ids, mlm_labels, nsp_labels)
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            new_p[k], new_m[k], new_v[k] = adamw_update(
                params[k], grads[k], opt_state["m"][k], opt_state["v"][k],
                t, learning_rate, beta1, beta2, eps, weight_decay)
        return new_p, {"step": step, "m": new_m, "v": new_v}, loss

    state_sh = adamw_state_shardings(mesh, opt_state, params)
    jitted = jax.jit(
        train_step,
        in_shardings=(shardings, state_sh, data_sh, data_sh, data_sh,
                      data_sh),
        out_shardings=(shardings, state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1))
    return params, opt_state, jitted
