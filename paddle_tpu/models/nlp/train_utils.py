"""Shared pieces of the compiled train-step factories (llama/bert/...).

One implementation of the ZeRO moment-sharding rule and the AdamW update
so the per-model factories can't drift (they previously carried verbatim
copies).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def with_memory_kind(sharding, kind):
    """``sharding.with_memory_kind(kind)`` when the backend exposes that
    memory space, else the sharding unchanged. CPU backends on some jax
    builds address only ``unpinned_host`` — there offload degrades to a
    no-op (nothing to offload to) instead of failing to build the
    optimizer state."""
    try:
        return sharding.with_memory_kind(kind)
    except (ValueError, TypeError):
        return sharding


def zero_like_sharded(mesh, shardings, name, v, accum_dtype=jnp.float32,
                      offload=False):
    """A zeros moment buffer for param ``v``: inherits the param's
    annotated axes, then (when a >1 'sharding' axis exists) shards the
    largest remaining divisible dim over it — ZeRO-1
    (~ group_sharded_optimizer_stage2.py:48 param segmentation).

    ``offload=True`` places the buffer in pinned host memory
    (~ group_sharded_stage3.py:58 offload): the jitted step declares the
    same memory kind in its in/out shardings, so XLA owns the
    host<->device DMA and can overlap it with compute — the TPU-native
    form of the reference's cudaMemcpyAsync offload stream."""
    sh = shardings[name]
    spec = list(sh.spec) + [None] * (v.ndim - len(sh.spec))
    if "sharding" in mesh.axis_names and mesh.shape.get("sharding", 1) > 1:
        for i in np.argsort([-s for s in v.shape]):
            i = int(i)
            if spec[i] is None and v.shape[i] % mesh.shape["sharding"] == 0:
                spec[i] = "sharding"
                break
    target = NamedSharding(mesh, P(*spec))
    if offload:
        target = with_memory_kind(target, "pinned_host")
    return jax.device_put(jnp.zeros(v.shape, accum_dtype), target)


def adamw_update(p, g, m, v, t, lr, beta1, beta2, eps, weight_decay,
                 accum_dtype=jnp.float32):
    """One decoupled-weight-decay Adam step on a single tensor; moments in
    ``accum_dtype``, param returned in its own dtype."""
    g = g.astype(accum_dtype)
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m2 / (1 - beta1 ** t)
    vhat = v2 / (1 - beta2 ** t)
    delta = mhat / (jnp.sqrt(vhat) + eps) \
        + weight_decay * p.astype(accum_dtype)
    return (p.astype(accum_dtype) - lr * delta).astype(p.dtype), m2, v2


def make_adamw_state(mesh, shardings, params, accum_dtype=jnp.float32,
                     offload=False):
    """step/m/v opt-state pytree with ZeRO-aware shardings; ``offload``
    pins the moments in host memory (see zero_like_sharded)."""
    return {
        # commit the step counter to the mesh: an uncommitted scalar's
        # aval (empty mesh) differs from the jit output's (mesh-attached)
        # and the mismatch silently RECOMPILES the whole train step on
        # its second call (~50s for BERT-base — found on chip)
        "step": jax.device_put(jnp.zeros((), jnp.int32),
                               NamedSharding(mesh, P())),
        "m": {k: zero_like_sharded(mesh, shardings, k, v, accum_dtype,
                                   offload)
              for k, v in params.items()},
        "v": {k: zero_like_sharded(mesh, shardings, k, v, accum_dtype,
                                   offload)
              for k, v in params.items()},
    }


def adamw_state_shardings(mesh, opt_state, params):
    return {"step": NamedSharding(mesh, P()),
            "m": {k: opt_state["m"][k].sharding for k in params},
            "v": {k: opt_state["v"][k].sharding for k in params}}
