from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertModel, bert_pretrain_step_factory)
from .gpt import (GPTConfig, GPTForCausalLM,  # noqa: F401
                  gpt_pretrain_step_factory)
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_train_step_factory,
)
from .moe import (MoEConfig, MoEForCausalLM,  # noqa: F401
                  moe_train_step_factory)
from .llama_decode import llama_decode_factory  # noqa: F401,E402
from .llama_decode import llama_paged_decode_factory  # noqa: F401,E402
from .llama_decode import llama_speculative_decode_factory  # noqa: F401,E402
from .llama_decode import llama_serving_decode_factory  # noqa: F401,E402
from .llama_decode import route_decode  # noqa: F401,E402
