from .bert import BertConfig, BertForPretraining, BertModel  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM  # noqa: F401
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_train_step_factory,
)
from .moe import MoEConfig, MoEForCausalLM  # noqa: F401
