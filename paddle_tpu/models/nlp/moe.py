"""MoE decoder LM — BASELINE config 5 (DeepSeekMoE / Qwen2-MoE slot).

Llama-style decoder where MLPs alternate with MoELayer (expert parallel
over the 'expert' mesh axis; dispatch einsum = compiled all_to_all).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ... import nn
from ...incubate.distributed.models.moe import MoELayer
from .llama import LlamaAttention, LlamaConfig, LlamaMLP


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 1024
    intermediate_size: int = 2816
    num_hidden_layers: int = 8
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2  # every Nth layer is MoE
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    aux_loss_weight: float = 0.01
    # DeepSeekMoE/Qwen2-MoE shape: dense "shared" experts run on EVERY
    # token alongside the routed ones (isolating common knowledge so the
    # fine-grained routed experts specialize); 0 = classic gshard/switch
    num_shared_experts: int = 0
    # width of the fused shared-expert SwiGLU; None = num_shared_experts
    # x intermediate_size (DeepSeek's same-width experts). Qwen-MoE uses
    # a shared expert WIDER than the routed ones (e.g. 20480 vs 2560),
    # which this overrides directly.
    shared_expert_intermediate: int | None = None
    # "indexed" = scatter/gather dispatch (O(T*k*H) data movement);
    # "einsum" = dense one-hot (T,E,C) oracle (O(T^2) MACs) for A/B
    dispatch_mode: str = "indexed"

    @staticmethod
    def tiny():
        return MoEConfig(vocab_size=256, hidden_size=64,
                         intermediate_size=128, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=4,
                         num_experts=4, moe_every=1)

    @staticmethod
    def qwen2_57b_a14b():
        """Qwen2-57B-A14B shape (BASELINE config 5): 64 fine-grained
        routed experts top-8 + one 20480-wide shared expert on every
        MoE layer, GQA attention. Full-size preset — shard 'expert'
        over EP and 'data'/'model' per the 4D factory for pod runs."""
        return MoEConfig(vocab_size=151936, hidden_size=3584,
                         intermediate_size=2560, num_hidden_layers=28,
                         num_attention_heads=28, num_key_value_heads=4,
                         num_experts=64, top_k=8, moe_every=1,
                         num_shared_experts=1,
                         shared_expert_intermediate=20480)

    @staticmethod
    def deepseek_tiny():
        """Fine-grained + shared-expert shape (BASELINE config 5's
        DeepSeekMoE/Qwen2-MoE family): many small routed experts, one
        always-on shared expert."""
        return MoEConfig(vocab_size=256, hidden_size=64,
                         intermediate_size=32, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=4,
                         num_experts=8, top_k=2, moe_every=1,
                         num_shared_experts=1)

    def as_llama(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
            dtype=jnp.float32)


class MoEDecoderLayer(nn.Layer):
    def __init__(self, config: MoEConfig, use_moe: bool):
        super().__init__()
        lc = config.as_llama()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.self_attn = LlamaAttention(lc)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self.shared_mlp = None
        if use_moe:
            self.mlp = MoELayer(config.hidden_size, config.intermediate_size,
                                config.num_experts,
                                gate="gshard" if config.top_k == 2
                                else "switch",
                                capacity_factor=config.capacity_factor,
                                top_k=config.top_k,
                                dispatch_mode=config.dispatch_mode)
            if config.num_shared_experts > 0:
                # always-on shared expert(s): one dense SwiGLU whose
                # intermediate width is n_shared x the routed experts'
                # (DeepSeekMoE isolates common knowledge here; routed
                # experts specialize)
                shared_w = config.shared_expert_intermediate \
                    or config.intermediate_size * config.num_shared_experts
                self.shared_mlp = LlamaMLP(dataclasses.replace(
                    lc, intermediate_size=shared_w))
        else:
            self.mlp = LlamaMLP(lc)
        self.use_moe = use_moe

    def forward(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        h = self.post_attention_layernorm(x)
        out = self.mlp(h)
        if self.shared_mlp is not None:
            out = out + self.shared_mlp(h)
        x = x + out
        return x


class MoEForCausalLM(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        # sigma=0.02 init (standard LM practice) rather than Embedding's
        # reference-matching N(0,1) default: the output head is TIED to
        # this table (forward() below), so N(0,1) would give initial
        # logits with std ~ sqrt(H) and a first-step loss ~9x ln(V)
        # (round-4 verdict: loss 49.9 where uniform prediction gives
        # ln 256 = 5.5). sigma=0.02 puts step-0 CE at ~ln V.
        from ...nn.initializer import Normal, ParamAttr
        self.embed_tokens = nn.Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=ParamAttr(initializer=Normal(0.0, 0.02)))
        self.layers = nn.LayerList([
            MoEDecoderLayer(config,
                            use_moe=(i % config.moe_every
                                     == config.moe_every - 1))
            for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def hidden_states(self, input_ids):
        """Final-norm hidden states — the head projection's input (the
        chunked-CE path fuses that projection into the loss)."""
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x)
        return self.norm(x)

    def forward(self, input_ids):
        x = self.hidden_states(input_ids)
        from ...ops.linalg import matmul
        return matmul(x, self.embed_tokens.weight, transpose_y=True)

    def aux_loss(self):
        total = None
        for layer in self.layers:
            if layer.use_moe and layer.mlp.aux_loss is not None:
                al = layer.mlp.aux_loss
                total = al if total is None else total + al
        if total is None:
            import paddle_tpu as paddle
            return paddle.zeros([])
        return total * self.config.aux_loss_weight

    def activated_params(self) -> int:
        """Parameters touched per token (MoE MFU accounting): everything
        except the routed experts, plus top_k/num_experts of them."""
        import numpy as np
        total = routed = 0
        for name, p in self.state_dict().items():
            n = int(np.prod(p.shape))
            total += n
            if ".mlp.w_in" in name or ".mlp.w_out" in name:
                routed += n
        cfg = self.config
        return total - routed + routed * cfg.top_k // cfg.num_experts


def moe_train_step_factory(model: MoEForCausalLM, mesh,
                           learning_rate=1e-4, weight_decay=0.01,
                           beta1=0.9, beta2=0.95, eps=1e-8,
                           remat=False, chunked_vocab_ce=None):
    """(params, opt_state, step) for compiled MoE causal-LM pretraining
    (BASELINE.md config 5: DeepSeekMoE / Qwen2-MoE, expert parallel).

    Same pjit pattern as bert_pretrain_step_factory: params per sharding
    annotation — MoELayer's expert-stacked weights carry
    P('expert', ...) specs, so a mesh with an 'expert' axis runs true
    expert parallelism (dispatch/combine einsums compile to all_to_all
    over ICI) with no factory-side special casing. Loss = CE of logits
    against POSITION-ALIGNED labels (the family convention shared with
    llama/bert factories and causal_lm_loss: callers shift, e.g.
    tokens[:, :-1] -> labels tokens[:, 1:]) + the gates' load-balancing
    aux loss.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...autograd import no_grad
    from ...core.tensor import Tensor
    from .llama import param_shardings
    from .train_utils import (adamw_state_shardings, adamw_update,
                              make_adamw_state)

    shardings = param_shardings(model, mesh)
    params = {k: jax.device_put(jnp.array(v._value, copy=True),
                                shardings[k])
              for k, v in model.state_dict().items()}
    opt_state = make_adamw_state(mesh, shardings, params)
    data_sh = NamedSharding(
        mesh, P("data" if "data" in mesh.axis_names else None))

    def forward_loss(params, tokens, labels):
        saved = model.tree_flatten_params()
        model.load_tree(params)
        try:
            with no_grad():
                if chunked_vocab_ce:
                    h = model.hidden_states(Tensor(tokens))._value
                    w_head = model.embed_tokens.weight._value
                else:
                    logits = model(Tensor(tokens))._value
                aux = model.aux_loss()._value
        finally:
            model.load_tree(saved)
        if chunked_vocab_ce:
            # fused head-projection + CE: the (B*S, V) logits are never
            # materialized (Qwen2-MoE's V=151936 makes them ~5 GB bf16
            # at B=8/S=2048)
            from ...ops.chunked_ce import chunked_causal_lm_loss
            ce = chunked_causal_lm_loss(h, w_head, labels,
                                        int(chunked_vocab_ce))
            return ce + aux.astype(jnp.float32)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(
            logits.reshape(-1, V).astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(
            logp, labels.reshape(-1)[:, None], -1)[:, 0]
        return jnp.mean(nll) + aux.astype(jnp.float32)

    loss_fn = jax.checkpoint(forward_loss) if remat else forward_loss

    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            new_p[k], new_m[k], new_v[k] = adamw_update(
                params[k], grads[k], opt_state["m"][k],
                opt_state["v"][k], t, learning_rate, beta1, beta2, eps,
                weight_decay)
        return new_p, {"step": step, "m": new_m, "v": new_v}, loss

    state_sh = adamw_state_shardings(mesh, opt_state, params)
    jitted = jax.jit(
        train_step,
        in_shardings=(shardings, state_sh, data_sh, data_sh),
        out_shardings=(shardings, state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1))
    return params, opt_state, jitted
