"""GPT — decoder-only transformer (PaddleNLP GPT capability slot)."""
from __future__ import annotations

import dataclasses

from ... import nn
from ...nn import functional as F


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    layer_norm_eps: float = 1e-5

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=128,
                         dropout=0.0)


class GPTBlock(nn.Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(c.hidden_size,
                                          c.num_attention_heads, c.dropout)
        self.ln_2 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.fc1 = nn.Linear(c.hidden_size, c.intermediate_size)
        self.fc2 = nn.Linear(c.intermediate_size, c.hidden_size)
        self.dropout = nn.Dropout(c.dropout)

    def forward(self, x, mask):
        x = x + self.attn(self.ln_1(x), attn_mask=mask)
        h = self.ln_2(x)
        return x + self.dropout(self.fc2(F.gelu(self.fc1(h))))


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList(
            [GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids):
        import paddle_tpu as paddle
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        mask = apply_op(
            "causal_mask",
            lambda: jnp.where(jnp.tril(jnp.ones((S, S), bool)), 0.0,
                              jnp.finfo(jnp.float32).min), nondiff=True)
        for blk in self.blocks:
            x = blk(x, mask)
        x = self.ln_f(x)
        from ...ops.linalg import matmul
        return matmul(x, self.wte.weight, transpose_y=True)

    def generate(self, input_ids, max_new_tokens=16):
        """Greedy decode (tied lm head). For compiled KV-cache serving use
        the Llama stack (llama_decode_factory); GPT keeps the simple
        recompute form the reference's generation API exposes."""
        import paddle_tpu as paddle
        import numpy as np
        out = input_ids
        for _ in range(int(max_new_tokens)):
            window = out
            if window.shape[1] > self.config.max_position_embeddings:
                window = window[:, -self.config.max_position_embeddings:]
            logits = self.forward(window)
            nxt = paddle.argmax(logits[:, -1, :], axis=-1)
            nxt_np = nxt.numpy().reshape(-1, 1).astype(np.int64)
            out = paddle.concat([out, paddle.to_tensor(nxt_np)], axis=1)
        return out


def gpt_pretrain_step_factory(model: GPTForCausalLM, mesh,
                              learning_rate=1e-4, weight_decay=0.01,
                              beta1=0.9, beta2=0.95, eps=1e-8):
    """(params, opt_state, step) for compiled GPT causal-LM pretraining —
    same pjit pattern and shared train_utils adamw as the llama/bert
    factories: params per sharding annotation (TP axes honored when
    annotated), moments ZeRO-sharded over 'sharding' when present, batch
    over 'data'. Dropout is inactive in the compiled path (traced under
    no_grad with the layer state untouched, like bert's factory)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...autograd import no_grad
    from ...core.tensor import Tensor
    from .llama import param_shardings
    from .train_utils import (adamw_state_shardings, adamw_update,
                              make_adamw_state)

    shardings = param_shardings(model, mesh)
    params = {k: jax.device_put(jnp.array(v._value, copy=True),
                                shardings[k])
              for k, v in model.state_dict().items()}
    opt_state = make_adamw_state(mesh, shardings, params)
    opt_sh = adamw_state_shardings(mesh, opt_state, params)
    data_sh = NamedSharding(
        mesh, P("data" if "data" in mesh.axis_names else None))

    def loss_fn(params, tokens, labels):
        saved = model.tree_flatten_params()
        was = model.training
        model.eval()  # deterministic dropout inside the trace
        model.load_tree(params)
        try:
            with no_grad():
                logits = model(Tensor(tokens))._value.astype(jnp.float32)
        finally:
            model.load_tree(saved)  # never leave tracers in the Layer
            if was:
                model.train()
        logp = jax.nn.log_softmax(logits, -1)
        return jnp.mean(
            -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0])

    def _step(params, opt_state, tokens, labels):
        tokens = jax.lax.with_sharding_constraint(tokens, data_sh)
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        t = (opt_state["step"] + 1).astype(jnp.float32)
        new_p, new_m, new_v = {}, {}, {}
        for k, p in params.items():
            new_p[k], new_m[k], new_v[k] = adamw_update(
                p, grads[k], opt_state["m"][k], opt_state["v"][k], t,
                learning_rate, beta1, beta2, eps, weight_decay)
        return new_p, {"step": opt_state["step"] + 1, "m": new_m,
                       "v": new_v}, loss

    # pin output shardings (ZeRO moments stay sharded step over step, no
    # recompile from drifting layouts) and donate the old params/opt_state
    # — same contract as the llama/bert factories
    step = jax.jit(
        _step,
        out_shardings=({k: shardings[k] for k in params}, opt_sh, None),
        donate_argnums=(0, 1))

    return params, opt_state, step
