"""GPT — decoder-only transformer (PaddleNLP GPT capability slot)."""
from __future__ import annotations

import dataclasses

from ... import nn
from ...nn import functional as F


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    dropout: float = 0.1
    layer_norm_eps: float = 1e-5

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=128,
                         dropout=0.0)


class GPTBlock(nn.Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(c.hidden_size,
                                          c.num_attention_heads, c.dropout)
        self.ln_2 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.fc1 = nn.Linear(c.hidden_size, c.intermediate_size)
        self.fc2 = nn.Linear(c.intermediate_size, c.hidden_size)
        self.dropout = nn.Dropout(c.dropout)

    def forward(self, x, mask):
        x = x + self.attn(self.ln_1(x), attn_mask=mask)
        h = self.ln_2(x)
        return x + self.dropout(self.fc2(F.gelu(self.fc1(h))))


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList(
            [GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids):
        import paddle_tpu as paddle
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op
        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        mask = apply_op(
            "causal_mask",
            lambda: jnp.where(jnp.tril(jnp.ones((S, S), bool)), 0.0,
                              jnp.finfo(jnp.float32).min), nondiff=True)
        for blk in self.blocks:
            x = blk(x, mask)
        x = self.ln_f(x)
        from ...ops.linalg import matmul
        return matmul(x, self.wte.weight, transpose_y=True)
